"""Online autotuning of eager-fusion parameters via Bayesian optimization.

TPU-native rebuild of the reference's parameter manager + GP/EI stack
(ref: horovod/common/parameter_manager.cc, optim/bayesian_optimization.cc,
optim/gaussian_process.cc [V], SURVEY.md §2.1): scores each sample window by
throughput (bytes/sec through the fusion pipeline), models score as a
Gaussian process over (log2 fusion_threshold, cycle_time_ms), and proposes
the next candidate by expected improvement. Where the reference maximizes EI
with LBFGS over Eigen matrices, we use dense candidate sampling over the
bounded 2-D box — same acquisition, simpler machinery, numpy only.

Enabled by HOROVOD_AUTOTUNE=1; HOROVOD_AUTOTUNE_LOG dumps the search.
Only the *eager* path is tuned — traced collectives are scheduled by XLA
and have no runtime parameters to tune (SURVEY.md §5.8).
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

# Search bounds: threshold 1 KB .. 512 MB (log2 scale), cycle 0.1 .. 25 ms
# (the reference tunes the same two knobs over similar ranges [V]).
_LOG2_THRESH_LO, _LOG2_THRESH_HI = 10.0, 29.0
_CYCLE_LO, _CYCLE_HI = 0.1, 25.0


class GaussianProcess:
    """GP regression with an RBF kernel on unit-box-normalized inputs
    (ref: gaussian_process.cc [V])."""

    def __init__(self, noise: float = 0.8, length_scale: float = 0.2):
        self.noise = noise
        self.length_scale = length_scale
        self._x: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self._alpha: Optional[np.ndarray] = None
        self._l_chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale**2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.atleast_2d(x)
        y = np.asarray(y, dtype=np.float64)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise**2
        self._l_chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._l_chol.T, np.linalg.solve(self._l_chol, yn)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        x = np.atleast_2d(x)
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._l_chol, ks.T)
        var = np.clip(1.0 - (v**2).sum(0), 1e-12, None)
        return mu * self._y_std + self._y_mean, np.sqrt(var) * self._y_std


def expected_improvement(
    mu: np.ndarray, sigma: np.ndarray, best: float, xi: float = 0.01
) -> np.ndarray:
    """EI acquisition (ref: bayesian_optimization.cc [V])."""
    from math import erf, sqrt

    z = (mu - best - xi) / sigma
    cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
    pdf = np.exp(-0.5 * z**2) / math.sqrt(2 * math.pi)
    return (mu - best - xi) * cdf + sigma * pdf


def make_gaussian_process(noise: float = 0.8, length_scale: float = 0.2):
    """Prefer the native GP core (csrc/gp.cc — the reference keeps this
    math in C++, optim/gaussian_process.cc [V]); fall back to numpy."""
    try:
        from .._native import loader as _native

        if _native.available():
            return _native.NativeGaussianProcess(
                noise=noise, length_scale=length_scale
            )
    except Exception:
        pass
    return GaussianProcess(noise=noise, length_scale=length_scale)


class BayesianOptimizer:
    """Propose-next-candidate loop over the (threshold, cycle) box."""

    def __init__(self, noise: float = 0.8, seed: int = 0):
        self._gp = make_gaussian_process(noise=noise)
        self._rng = np.random.default_rng(seed)
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []

    @staticmethod
    def _normalize(threshold_log2: float, cycle_ms: float) -> np.ndarray:
        return np.array(
            [
                (threshold_log2 - _LOG2_THRESH_LO)
                / (_LOG2_THRESH_HI - _LOG2_THRESH_LO),
                (cycle_ms - _CYCLE_LO) / (_CYCLE_HI - _CYCLE_LO),
            ]
        )

    @staticmethod
    def _denormalize(p: np.ndarray) -> Tuple[int, float]:
        log2t = _LOG2_THRESH_LO + p[0] * (_LOG2_THRESH_HI - _LOG2_THRESH_LO)
        cycle = _CYCLE_LO + p[1] * (_CYCLE_HI - _CYCLE_LO)
        return int(2 ** round(log2t)), float(round(cycle, 2))

    def observe(self, threshold_bytes: int, cycle_ms: float, score: float):
        self._xs.append(
            self._normalize(math.log2(max(threshold_bytes, 1)), cycle_ms)
        )
        self._ys.append(score)

    def suggest(self) -> Tuple[int, float]:
        if len(self._xs) < 2:
            p = self._rng.uniform(size=2)
            return self._denormalize(p)
        self._gp.fit(np.stack(self._xs), np.array(self._ys))
        cands = self._rng.uniform(size=(256, 2))
        mu, sigma = self._gp.predict(cands)
        ei = expected_improvement(mu, sigma, best=max(self._ys))
        return self._denormalize(cands[int(np.argmax(ei))])

    def best(self) -> Tuple[int, float]:
        i = int(np.argmax(self._ys))
        return self._denormalize(self._xs[i])


class ParameterManager:
    """Drives sampling windows over live traffic (ref: parameter_manager.cc
    Tune()/Step() [V]). The fusion manager calls record() once per flush;
    we aggregate steps_per_sample flushes into one score sample."""

    def __init__(
        self,
        initial_threshold: int,
        initial_cycle_ms: float,
        warmup_samples: int = 3,
        steps_per_sample: int = 10,
        max_samples: int = 20,
        gp_noise: float = 0.8,
        log_path: Optional[str] = None,
    ):
        self._threshold = initial_threshold
        self._cycle_ms = initial_cycle_ms
        self._warmup_left = warmup_samples
        self._steps_per_sample = steps_per_sample
        self._max_samples = max_samples
        self._optimizer = BayesianOptimizer(noise=gp_noise)
        self._log_path = log_path
        self._bytes = 0
        self._wire_bytes = 0
        self._seconds = 0.0
        self._steps = 0
        self._samples = 0
        self._frozen = False

    @classmethod
    def from_config(cls, cfg) -> "ParameterManager":
        return cls(
            initial_threshold=cfg.fusion_threshold_bytes,
            initial_cycle_ms=cfg.cycle_time_ms,
            warmup_samples=cfg.autotune_warmup_samples,
            steps_per_sample=cfg.autotune_steps_per_sample,
            max_samples=cfg.autotune_bayes_opt_max_samples,
            gp_noise=cfg.autotune_gaussian_process_noise,
            log_path=cfg.autotune_log,
        )

    def current(self) -> Tuple[int, float]:
        return self._threshold, self._cycle_ms

    @property
    def frozen(self) -> bool:
        return self._frozen

    def record(
        self,
        bytes_: int,
        seconds: float,
        wire_bytes: Optional[int] = None,
    ) -> None:
        """One flush sample. ``bytes_`` is USEFUL payload; ``wire_bytes``
        (>= bytes_) is what actually moved, bucket padding included. The
        score is goodput — useful bytes per second — so a parameter
        choice that pads more pays for its padding in time without
        being credited for the padded bytes; the wire/pad split is
        still logged and exported so the padding cost stays visible."""
        if self._frozen:
            return
        self._bytes += bytes_
        self._wire_bytes += wire_bytes if wire_bytes is not None else bytes_
        self._seconds += seconds
        self._steps += 1
        if self._steps < self._steps_per_sample:
            return
        score = self._bytes / max(self._seconds, 1e-9)
        pad = self._wire_bytes - self._bytes
        self._log(score, note=f"pad_bytes={pad}" if pad else "")
        from .metrics import registry as _metrics

        _metrics.update(
            "autotune",
            {
                "score": score,
                "sample_bytes": self._bytes,
                "sample_wire_bytes": self._wire_bytes,
                "sample_pad_bytes": pad,
            },
        )
        self._bytes, self._wire_bytes = 0, 0
        self._seconds, self._steps = 0.0, 0
        if self._warmup_left > 0:
            self._warmup_left -= 1
            return
        self._samples += 1
        self._optimizer.observe(self._threshold, self._cycle_ms, score)
        if self._samples >= self._max_samples:
            self._threshold, self._cycle_ms = self._optimizer.best()
            self._frozen = True
            self._log(None, note="frozen")
        else:
            self._threshold, self._cycle_ms = self._optimizer.suggest()

    def _log(self, score, note: str = "") -> None:
        if not self._log_path:
            return
        with open(self._log_path, "a") as f:
            f.write(
                f"threshold={self._threshold} cycle_ms={self._cycle_ms} "
                f"score={'' if score is None else f'{score:.3e}'} {note}\n"
            )


class _GoodputBandit:
    """Shared explore-then-exploit core of the discrete tuners: per
    (key, candidate) goodput accounting (useful bytes per second),
    ``trials`` exploration visits round-robin, then argmax. A bandit,
    not a GP: these decisions are small discrete menus, where the GP's
    machinery buys nothing (it remains the right tool for the
    continuous (threshold, cycle) box above).

    Observations are durable: :meth:`state_dict` /
    :meth:`load_state_dict` serialize them, and the module-level
    :func:`warm_start` / :func:`persist` pair keys the file by
    (tuner name, topology fingerprint) under ``HOROVOD_TUNER_CACHE``
    so a fleet explores once instead of per-process per-run — the
    per-hop keyspaces (PR 10's (bucket-tier, hop), PR 12's
    (alltoall, hop)) made cold-start strictly more expensive."""

    def __init__(self, trials: int = 3):
        self.trials = max(int(trials), 1)
        # (key, candidate) -> [useful_bytes_total, seconds_total, n]
        self._obs = {}

    # -- persistence --------------------------------------------------

    def state_dict(self) -> dict:
        """JSON-serializable snapshot of every observation. Keys are
        tuples of str/int/float (the tuners' contract) — encoded as
        lists and rebuilt as tuples on load."""
        return {
            "trials": self.trials,
            "obs": [
                [list(key) if isinstance(key, tuple) else [key],
                 cand, s[0], s[1], s[2]]
                for (key, cand), s in self._obs.items()
            ],
        }

    def load_state_dict(self, state: dict) -> int:
        """Merge a snapshot back in (existing observations win — live
        measurements beat stale disk state). Returns the number of
        (key, candidate) entries adopted; malformed entries are
        skipped — a corrupt cache must never break the tuner."""
        adopted = 0
        for row in state.get("obs", ()):
            try:
                key_list, cand, by, secs, n = row
                key = tuple(key_list)
                if isinstance(cand, list):
                    cand = tuple(cand)
                entry = (key, cand)
                if entry in self._obs:
                    continue
                self._obs[entry] = [float(by), float(secs), int(n)]
                adopted += 1
            except (TypeError, ValueError):
                continue
        return adopted

    def _stats(self, key, cand):
        return self._obs.setdefault((key, cand), [0.0, 0.0, 0])

    def needs_trial(self, key, cand) -> bool:
        """True while this (key, candidate) is still under-explored."""
        return self._obs.get((key, cand), (0, 0, 0))[2] < self.trials

    def record(self, key, cand, useful_bytes: int, seconds: float) -> None:
        s = self._stats(key, cand)
        s[0] += float(useful_bytes)
        s[1] += float(seconds)
        s[2] += 1

    def goodput(self, key, cand) -> float:
        s = self._obs.get((key, cand))
        if not s or s[2] == 0:
            return 0.0
        return s[0] / max(s[1], 1e-9)

    def _choose_among(self, key, cands):
        """Single-candidate shortcut (marked fully trialed so callers
        never pay trial synchronization for a decision with one
        possible answer), else explore round-robin, else exploit the
        goodput argmax."""
        if len(cands) == 1:
            s = self._stats(key, cands[0])
            s[2] = max(s[2], self.trials)
            return cands[0]
        for c in cands:
            if self.needs_trial(key, c):
                return c
        return max(cands, key=lambda c: self.goodput(key, c))


class WireTuner(_GoodputBandit):
    """Per-bucket-tier online choice of the fused wire format
    (``HOROVOD_FUSION_WIRE=auto``) by goodput — useful bytes per second
    of dispatch wall time, so the measurement naturally charges each
    format its own quant tax and credits it for the wire bytes it
    removes. The fusion manager BLOCKS on the dispatch result for
    exactly the ``needs_trial`` observations — async dispatch wall time
    is format-independent and would teach the tuner nothing — and stops
    recording once the trials are in (explore-then-freeze).

    Two-level wires key the bandit PER HOP — callers append the hop to
    the bucket key (``(bucket-tier..., 'intra'|'inter')``), so goodput
    can converge on bf16-intra / int8-inter independently: the intra
    menu never includes int8 (ICI is fast; the quant tax cannot pay
    for itself inside a slice) while the inter key is sized by the
    1/L shard the DCN actually carries. Flat wires keep the plain
    bucket key — the keyspaces never mix.

    Two static priors bound the exploration:

    * buckets under ``min_int8_bytes`` never try int8 — the per-dispatch
      quantize tax is O(payload)+fixed while the wire saving is
      O(payload), so below a payload floor the tax always wins (the
      crossover bench_int8.py measures);
    * ``candidates`` restricts the menu (int8 only where the op/dtype
      qualify — the fusion manager filters before asking).
    """

    CANDIDATES = ("fp32", "bf16", "int8")

    def __init__(self, min_int8_bytes: int = 64 * 1024, trials: int = 3):
        super().__init__(trials=trials)
        self.min_int8_bytes = int(min_int8_bytes)

    def choose(
        self, bucket_key, payload_bytes: int, candidates=None,
        itemsize: int = 4,
    ) -> str:
        """Pick the wire format for one fused dispatch of this bucket
        tier. Tiny buckets short-circuit to fp32/bf16 (never int8);
        candidates that cannot shrink the payload are dropped (bf16
        saves nothing on an already-2-byte fp16/bf16 payload, and the
        cast would silently truncate mantissa for free); otherwise
        under-explored candidates are tried round-robin and the steady
        state is the goodput argmax."""
        cands = list(candidates if candidates is not None else self.CANDIDATES)
        if payload_bytes < self.min_int8_bytes:
            cands = [c for c in cands if c != "int8"]
        if itemsize <= 2:
            cands = [c for c in cands if c != "bf16"]
        if not cands:
            return "fp32"
        return self._choose_among(bucket_key, cands)


class OverlapTuner(_GoodputBandit):
    """Choice of the backward-interleaved exchange's bucket count
    (``ops/overlap.py``) by WHOLE-STEP goodput — useful gradient bytes
    per second of step wall time. The bucket schedule trades two
    opposing costs the byte model cannot rank a priori: more buckets
    expose more backward compute to hide wire time behind (win), but
    each bucket pays a collective launch + a smaller message's worse
    bandwidth utilization (loss). Scoring the STEP, not the collective,
    lets the measurement settle it — the same reasoning that moved the
    ParameterManager's score to goodput.

    Driven by the STEP HARNESS, not from inside the compiled step: a
    bucket-count change changes the compiled program, so each candidate
    is its own jitted step — the training loop (or bench:
    ``bench_overlap.py`` runs exactly this loop) times a few chained,
    honestly-synced steps per candidate, feeds ``record``, and rebuilds
    its step with ``choose``'s answer once exploration drains. The
    caller owns the timing discipline (docs/perf.md §measurement
    integrity) or the tuner learns dispatch overhead, not overlap.

    ``min_bucket_bytes`` is the static prior bounding the explore set:
    a candidate whose per-bucket size would fall under the floor can
    only lose (launch overhead is O(1) per bucket while the hidden
    wire time is O(bucket bytes)), so it is never tried — the
    ``HOROVOD_OVERLAP_MIN_BYTES`` knob, autotuned-path edition.
    """

    CANDIDATES = (1, 2, 4, 8, 16)

    def __init__(
        self,
        min_bucket_bytes: int = 1 << 20,
        trials: int = 3,
        candidates=None,
    ):
        super().__init__(trials=trials)
        self.min_bucket_bytes = int(min_bucket_bytes)
        self.candidates = tuple(
            candidates if candidates is not None else self.CANDIDATES
        )

    def viable(self, total_bytes: int):
        """Candidates whose balanced bucket size clears the byte floor
        (1 always qualifies — the monolithic schedule is the control)."""
        return tuple(
            c
            for c in self.candidates
            if c == 1 or total_bytes // c >= self.min_bucket_bytes
        )

    def choose(self, step_key, total_bytes: int) -> int:
        return self._choose_among(step_key, self.viable(total_bytes))


class CapacityTuner(_GoodputBandit):
    """Online choice of the MoE dispatch's ``capacity_factor``
    (``parallel/moe.py``) by KEPT-token goodput, fed by the per-expert
    load counters the dispatch already produces (``MoEStats``): a
    higher factor drops fewer tokens but pays a proportionally larger
    dispatch buffer (wire bytes, expert pad FLOPs); a lower one is
    cheap until hot experts overflow — and hot experts ARE stragglers,
    so the drop counters are the load-imbalance signal the byte model
    cannot rank a priori. Scoring kept tokens per second of step wall
    time lets the measurement settle it, exactly the OverlapTuner's
    reasoning — and like the bucket count, capacity is a COMPILE-TIME
    shape: the step harness times a few honestly-synced steps per
    candidate across recompiles (bench_moe.py ``ab_captuned`` shows
    the loop), never inside one compiled step.

    ``observe_load`` additionally folds the raw histogram into
    per-candidate drop-rate / imbalance summaries, which ``choose``
    uses as a hard prior: a candidate whose measured drop rate exceeds
    ``max_drop_rate`` after its trials is never exploited — dropped
    tokens are silently-degraded model quality, not just lost goodput.
    The same summaries feed the per-rank expert-load publications
    through the rendezvous KV (elastic/worker.py publish_expert_load).
    """

    CANDIDATES = (1.0, 1.25, 1.5, 2.0)

    def __init__(
        self,
        trials: int = 3,
        candidates=None,
        max_drop_rate: float = 0.2,
    ):
        super().__init__(trials=trials)
        self.candidates = tuple(
            candidates if candidates is not None else self.CANDIDATES
        )
        self.max_drop_rate = float(max_drop_rate)
        # (key, cand) -> [dropped_total, routed_total, hot_max, n_loads]
        self._loads = {}

    def observe_load(
        self, key, cand, expert_tokens, dropped: float, total: float,
        seconds: Optional[float] = None,
    ) -> None:
        """One step's load counters for (key, candidate):
        ``expert_tokens`` is the kept-token histogram ([E_total]),
        ``dropped``/``total`` the overflow and routed counts
        (``MoEStats`` fields, host floats). With ``seconds`` the call
        also feeds the goodput ledger (kept tokens as the useful
        quantity)."""
        tokens = [float(t) for t in expert_tokens]
        s = self._loads.setdefault(
            (key, cand), [0.0, 0.0, 0.0, 0, max(len(tokens), 1)]
        )
        s[0] += float(dropped)
        s[1] += float(total)
        s[2] = max(s[2], max(tokens, default=0.0))
        s[3] += 1
        s[4] = max(s[4], len(tokens))
        if seconds is not None:
            kept = float(total) - float(dropped)
            self.record(key, cand, kept, seconds)

    def drop_rate(self, key, cand) -> float:
        s = self._loads.get((key, cand))
        if not s or s[1] <= 0:
            return 0.0
        return s[0] / s[1]

    def imbalance(self, key, cand) -> float:
        """Hottest-expert load as a multiple of the per-step PER-EXPERT
        mean kept tokens — the hot-experts-are-stragglers meter (1.0 =
        perfectly balanced)."""
        s = self._loads.get((key, cand))
        if not s or s[3] == 0 or s[1] <= s[0]:
            return 1.0
        mean_kept = (s[1] - s[0]) / s[3] / max(s[4], 1)
        if mean_kept <= 0:
            return 1.0
        return s[2] / mean_kept

    def choose(self, key) -> float:
        cands = [
            c
            for c in self.candidates
            if self.needs_trial(key, c)
            or self.drop_rate(key, c) <= self.max_drop_rate
        ]
        if not cands:
            # every candidate overflows past the bound: take the
            # largest buffer — it drops least
            return max(self.candidates)
        return self._choose_among(key, tuple(cands))

    def state_dict(self) -> dict:
        d = super().state_dict()
        d["loads"] = [
            [list(key) if isinstance(key, tuple) else [key],
             cand, s[0], s[1], s[2], s[3], s[4]]
            for (key, cand), s in self._loads.items()
        ]
        return d

    def load_state_dict(self, state: dict) -> int:
        adopted = super().load_state_dict(state)
        for row in state.get("loads", ()):
            try:
                key_list, cand, dropped, total, hot, n, ne = row
                entry = (tuple(key_list), cand)
                if entry in self._loads:
                    continue
                self._loads[entry] = [
                    float(dropped), float(total), float(hot), int(n),
                    int(ne),
                ]
            except (TypeError, ValueError):
                continue
        return adopted


# ---------------------------------------------------------------------------
# Persistent tuner state (HOROVOD_TUNER_CACHE, ROADMAP item 1a).
#
# Exploration is the expensive half of a bandit whose keyspace grew
# per-hop (PR 10) and per-collective-family (PR 12): every process of
# every run used to pay `trials` deliberately-slow synchronized
# dispatches per (key, candidate). Persisting the observations keyed by
# (tuner name, topology fingerprint) lets a restarted — or freshly
# scheduled — job start from the fleet's measurements and skip straight
# to exploitation. The fingerprint pins everything that changes what a
# measurement MEANS: world size, the two-level split, and the backend.
# ---------------------------------------------------------------------------


def topology_fingerprint() -> str:
    """``w<world>-l<intra>-<platform>`` of the current process — the
    cache key namespace for persisted tuner state. Falls back to the
    env contract before hvd.init (trace-time tuners may run first)."""
    import jax

    from . import basics as _basics
    from .config import Config
    from .topology import detect_intra_size

    if _basics.is_initialized():
        topo = _basics.state().topology
        world = topo.size
        intra = topo.intra_size
    else:
        cfg = Config.from_env()
        world = cfg.size or len(jax.devices())
        intra = detect_intra_size(
            jax.devices(), jax.local_device_count(), jax.process_count()
        )
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    return f"w{world}-l{intra}-{platform}"


def tuner_cache_path(
    name: str, fingerprint: Optional[str] = None,
    base: Optional[str] = None,
) -> Optional[str]:
    """The persisted-state file for one tuner, or None when no cache
    directory is configured (HOROVOD_TUNER_CACHE / explicit base)."""
    import os

    if base is None:
        base = os.environ.get("HOROVOD_TUNER_CACHE") or None
    if not base:
        return None
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    return os.path.join(base, f"{name}-{fingerprint}.json")


def warm_start(
    tuner: _GoodputBandit, name: str,
    fingerprint: Optional[str] = None, base: Optional[str] = None,
) -> int:
    """Load persisted observations into ``tuner`` (existing live
    entries win). Returns the number of entries adopted; 0 when no
    cache is configured, the file is absent, or it is corrupt — warm
    start is best-effort by design, cold start is always correct."""
    import json
    import os

    path = tuner_cache_path(name, fingerprint, base)
    if not path or not os.path.exists(path):
        return 0
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        return 0
    if not isinstance(state, dict):
        return 0
    n = tuner.load_state_dict(state)
    if n:
        from .metrics import registry as _metrics

        _metrics.counter("autotune.warm_started", n)
    return n


def _merge_rows(own, disk):
    """Union of state rows keyed by (key, candidate) — the tuner's own
    rows win. Rows are ``[key_list, cand, ...]``."""
    def _k(row):
        cand = row[1]
        return (tuple(row[0]), tuple(cand) if isinstance(cand, list) else cand)

    seen = {_k(r) for r in own}
    return list(own) + [r for r in disk if _k(r) not in seen]


def persist(
    tuner: _GoodputBandit, name: str,
    fingerprint: Optional[str] = None, base: Optional[str] = None,
) -> Optional[str]:
    """Write ``tuner``'s observations to the cache (tmp+rename — a
    killed process can never leave a torn file), MERGED with whatever
    is already on disk (rows this tuner never saw are kept; its own
    rows win): several tuners legitimately share one file — the fused
    dispatcher's WireTuner (allreduce keys) and the trace-time shared
    tuner (alltoall keys) both persist under ``wire`` — and a plain
    overwrite would have the last atexit writer discard the other's
    run. Returns the path, or None when no cache is configured / the
    write failed (best-effort: persistence must never take a training
    loop down)."""
    import json
    import os
    import tempfile

    path = tuner_cache_path(name, fingerprint, base)
    if not path:
        return None
    state = tuner.state_dict()
    try:
        with open(path) as f:
            disk = json.load(f)
    except (OSError, ValueError):
        disk = None
    if isinstance(disk, dict):
        for field in ("obs", "loads"):
            if field in state or field in disk:
                state[field] = _merge_rows(
                    state.get(field, []), disk.get(field, [])
                )
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        return None
    return path


_shared_wire_tuner: Optional[WireTuner] = None


def shared_wire_tuner() -> WireTuner:
    """The process-wide WireTuner for TRACE-TIME wire decisions (the
    MoE alltoall's ``(alltoall, payload-bucket, dtype, hop)`` keys —
    compile-time choices consulted while tracing, unlike the fusion
    manager's per-dispatch instance). Warm-started from
    HOROVOD_TUNER_CACHE on first use and persisted at exit alongside
    it (same ``wire`` namespace: the keyspaces are disjoint by
    construction — (alltoall, ...) vs (allreduce, ...) — so one file
    serves both)."""
    global _shared_wire_tuner
    if _shared_wire_tuner is None:
        from .config import Config

        cfg = Config.from_env()
        _shared_wire_tuner = WireTuner(
            min_int8_bytes=cfg.fusion_wire_min_bytes
        )
        warm_start(_shared_wire_tuner, "wire")
        register_persist_at_exit(_shared_wire_tuner, "wire")
    return _shared_wire_tuner


_shared_overlap_tuner: Optional[OverlapTuner] = None
_shared_capacity_tuner: Optional[CapacityTuner] = None


def shared_overlap_tuner(**kwargs) -> OverlapTuner:
    """The process-wide OverlapTuner with durable state — the tuner-
    persistence parity the WireTuner got in PR 12, extended to the
    bucket-count decision (ROADMAP item 1a): warm-started from
    ``HOROVOD_TUNER_CACHE`` under the ``overlap`` name (topology-
    fingerprinted) on first use and persisted at exit, so a restarted
    step harness skips straight to exploitation instead of re-timing
    every bucket-count candidate. First call's ``kwargs`` win
    (min_bucket_bytes / trials / candidates); observations merge with
    disk on persist like every tuner (autotune.persist)."""
    global _shared_overlap_tuner
    if _shared_overlap_tuner is None:
        _shared_overlap_tuner = OverlapTuner(**kwargs)
        warm_start(_shared_overlap_tuner, "overlap")
        register_persist_at_exit(_shared_overlap_tuner, "overlap")
    return _shared_overlap_tuner


def shared_capacity_tuner(**kwargs) -> CapacityTuner:
    """The process-wide CapacityTuner with durable state (same parity:
    warm-start + persist-at-exit under ``capacity``, keyed by the
    topology fingerprint). The drop-rate/imbalance load ledger rides
    the snapshot too (CapacityTuner.state_dict), so the hard
    ``max_drop_rate`` prior survives restarts along with the goodput
    observations."""
    global _shared_capacity_tuner
    if _shared_capacity_tuner is None:
        _shared_capacity_tuner = CapacityTuner(**kwargs)
        warm_start(_shared_capacity_tuner, "capacity")
        register_persist_at_exit(_shared_capacity_tuner, "capacity")
    return _shared_capacity_tuner


def reset_shared_tuners() -> None:
    """Drop the shared overlap/capacity tuners (tests)."""
    global _shared_overlap_tuner, _shared_capacity_tuner
    _shared_overlap_tuner = None
    _shared_capacity_tuner = None


_persist_registry = []
_persist_hook_installed = [False]


def register_persist_at_exit(tuner: _GoodputBandit, name: str) -> None:
    """Arrange for ``tuner`` to be persisted at interpreter exit (one
    atexit hook for every registered tuner; no-ops without a cache
    dir). Registration is idempotent per (id(tuner), name)."""
    import atexit

    entry = (id(tuner), name)
    if any(e == entry for e, _ in _persist_registry):
        return
    _persist_registry.append((entry, (tuner, name)))
    if not _persist_hook_installed[0]:
        _persist_hook_installed[0] = True

        def _flush():
            for _, (t, n) in list(_persist_registry):
                try:
                    persist(t, n)
                except Exception:
                    pass

        atexit.register(_flush)
