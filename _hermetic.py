"""Sandbox-hermetic env construction for harness subprocesses.

One place owns the recipe for keeping a child process off the real TPU
chip: strip PALLAS_AXON_POOL_IPS (the gate that makes the sandbox's
sitecustomize register the TPU PJRT plugin), force JAX_PLATFORMS=cpu,
and (optionally) set the simulated host-device count — replacing any
existing count flag while preserving unrelated XLA_FLAGS.

Used by bench*.py, __graft_entry__.py and tests/test_examples.py.
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def with_device_count(flags: str, n_devices: int) -> str:
    """Return XLA_FLAGS with the host-device-count set to n_devices,
    replacing an existing count flag and keeping everything else."""
    flags = re.sub(rf"{_COUNT_FLAG}=\d+", "", flags or "")
    return " ".join(flags.split() + [f"{_COUNT_FLAG}={n_devices}"])


def hermetic_cpu_env(n_devices=None, base=None):
    """A copy of the environment guaranteed to run JAX on the host CPU."""
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        env["XLA_FLAGS"] = with_device_count(env.get("XLA_FLAGS"), n_devices)
    return env
