"""ZeRO-1-style sharded weight update for data-parallel training.

Beyond-parity, TPU-first (the reference has no analog): instead of
allreducing gradients and running the optimizer replicated, each rank

1. **reduce-scatters** the gradients (each rank receives the reduced
   1/N shard — half the wire bytes of a ring allreduce),
2. runs the optimizer update on its shard only (optimizer state — Adam
   moments etc. — lives sharded, 1/N of the memory per rank), then
3. **all-gathers** the parameter updates (the other half of the bytes).

Total communication equals one ring allreduce; optimizer math and
state memory drop to 1/N. This is the XLA "automatic cross-replica
sharding of weight update" / ZeRO-1 recipe (PAPERS.md: Xu et al.,
arXiv:2004.13336 — pattern reference only) expressed with explicit
collectives so it composes with the rest of the shard_map stack.

Contract:

* ``opt = ShardedDistributedOptimizer(optax.adam(1e-3))``
* ``state = opt.init(params)`` — OUTSIDE jit/shard_map. Every state
  leaf gains a leading ``world`` axis (rank r's shard at index r;
  scalar leaves like Adam's ``count`` are broadcast), so the whole
  state threads through ``jax.shard_map`` with a uniform
  ``P(WORLD_AXIS)`` spec.
* ``updates, state = opt.update(grads, state, params)`` — INSIDE
  ``shard_map`` over the world axis, full (replicated-shape) grads and
  params in, full updates out.

Supported inner transforms: elementwise ones (sgd, momentum, adam,
adamw, rmsprop, ...). **Caller responsibility** (optax transforms are
opaque closures — not detectable at init): norm-based transforms like
``clip_by_global_norm`` would compute shard-LOCAL norms inside the
sharded update and silently train wrong; apply gradient clipping to
the full gradients BEFORE this wrapper instead.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .common.topology import WORLD_AXIS
from .ops.reduction_ops import Average, ReduceOp, Sum, resolve_op


def _pad_to(flat, n):
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _shard_host(x, n, r):
    """Host-side shard r of array x (init path, outside jit)."""
    x = jnp.asarray(x)
    if x.ndim == 0:
        return x
    flat = _pad_to(x.reshape(-1), n)
    return flat.reshape(n, -1)[r]


def _shard_dyn(x, n, idx):
    """Traced shard selection by the rank's axis_index (update path)."""
    flat = _pad_to(x.reshape(-1), n)
    return jax.lax.dynamic_index_in_dim(
        flat.reshape(n, -1), idx, axis=0, keepdims=False
    )


class ShardedDistributedOptimizer:
    """Data-parallel optimizer with reduce-scatter/all-gather weight
    update and 1/world-sharded optimizer state (module docstring)."""

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        op: Optional[ReduceOp] = None,
        average: Optional[bool] = None,
        axis_name: str = WORLD_AXIS,
        world: Optional[int] = None,
    ):
        self._inner = optimizer
        self._op = resolve_op(op, average)
        if self._op not in (Sum, Average):
            raise NotImplementedError(
                "ShardedDistributedOptimizer supports op=Sum/Average "
                "(Adasum's recursive combine needs full gradients)"
            )
        self._axis = axis_name
        self._world = world

    # -- init (outside jit) ------------------------------------------------
    def init(self, params):
        from .common import basics

        n = self._world or basics.size()
        self._world = n
        shard_states = [
            self._inner.init(
                jax.tree_util.tree_map(
                    lambda p: _shard_host(p, n, r), params
                )
            )
            for r in range(n)
        ]
        # stack rank-major: every leaf gets a leading world axis, so the
        # state rides shard_map with ONE spec: P(axis_name)
        return jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
            *shard_states,
        )

    # -- update (inside shard_map over axis_name) --------------------------
    def update(self, grads, state, params):
        n = jax.lax.axis_size(self._axis)
        if self._world is not None and n != self._world:
            raise ValueError(
                f"world changed between init ({self._world}) and update "
                f"({n}): re-run init(params) after a topology change "
                "(elastic restarts rebuild optimizer state)"
            )
        idx = jax.lax.axis_index(self._axis)
        # shard_map hands each rank its [1, ...] state slice
        local_state = jax.tree_util.tree_map(lambda x: x[0], state)

        # 0-d leaves (scalar temperature etc.) stay replicated — exactly
        # like init's _shard_host — so state shapes are stable step-over-
        # step (a shape flip would force a retrace and break donation)
        def rs(g):
            if g.ndim == 0:
                red = jax.lax.psum(g, self._axis)
                return red / n if self._op == Average else red
            flat = _pad_to(g.reshape(-1), n).reshape(n, -1)
            red = jax.lax.psum_scatter(
                flat, self._axis, scatter_dimension=0, tiled=False
            )
            if self._op == Average:
                red = red / n
            return red

        g_sh = jax.tree_util.tree_map(rs, grads)
        p_sh = jax.tree_util.tree_map(
            lambda p: p if p.ndim == 0 else _shard_dyn(p, n, idx), params
        )
        upd_sh, new_local = self._inner.update(g_sh, local_state, p_sh)

        def gather(u, p):
            if p.ndim == 0:
                return u
            full = jax.lax.all_gather(u, self._axis, axis=0).reshape(-1)
            return full[: p.size].reshape(p.shape).astype(u.dtype)

        upd = jax.tree_util.tree_map(gather, upd_sh, params)
        new_state = jax.tree_util.tree_map(
            lambda x: x[None], new_local
        )
        return upd, new_state

    def state_spec(self):
        """The single PartitionSpec for the whole state pytree in
        shard_map in_specs/out_specs."""
        from jax.sharding import PartitionSpec as P

        return P(self._axis)
