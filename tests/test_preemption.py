"""Preemption handling tests: signal latching in-process, and a real
SIGTERM to a training subprocess that must leave a resumable durable
checkpoint (the TPU analog of the reference's kill-based elastic
integration tests, SURVEY.md §4.3)."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _rearm_kv_polls():
    """The preemption handler latches the PROCESS-WIDE KV poll-shutdown
    event (by design — a preempted worker must stop spinning against
    the driver). Tests that fire it must re-arm the latch, or every
    later KV wait() in the suite silently aborts on its first poll
    (test_runner's version-consistency check was the victim)."""
    yield
    from horovod_tpu.runner import rendezvous as _rdv

    _rdv.reset_poll_shutdown()


def test_handler_latches_and_chains():
    from horovod_tpu.preemption import PreemptionHandler

    seen = []
    prev_called = []
    signal.signal(signal.SIGUSR1, lambda s, f: prev_called.append(s))
    handler = PreemptionHandler(
        signals=(signal.SIGUSR1,), on_preempt=lambda: seen.append(1)
    )
    try:
        assert not handler.should_stop()
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0.1)
        assert handler.should_stop()
        assert seen == [1]
        assert prev_called == [signal.SIGUSR1]  # chained
    finally:
        handler.uninstall()
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)


def test_uninstall_restores():
    from horovod_tpu.preemption import PreemptionHandler

    original = signal.getsignal(signal.SIGUSR2)
    handler = PreemptionHandler(signals=(signal.SIGUSR2,))
    handler.uninstall()
    assert signal.getsignal(signal.SIGUSR2) == (
        original if original is not None else signal.SIG_DFL
    )


def test_graceful_shutdown_drain_ordering(monkeypatch):
    """The SIGTERM sequence contract: registered drains (instance, then
    module hooks, each in registration order) → flight-recorder dump →
    durable persist. The serving frontend depends on running FIRST —
    its in-flight requests must finish while the process is fully
    alive, before observability and durability take the grace window."""
    from horovod_tpu import preemption
    from horovod_tpu.common import telemetry

    order = []

    class _State:
        def persist(self):
            order.append("persist")

        def wait_until_finished(self):
            order.append("wait")

    hub = telemetry.hub()
    monkeypatch.setattr(hub, "dump", lambda: order.append("telemetry"))
    gs = preemption.GracefulShutdown(_State())
    gs.register_drain(lambda: order.append("instance_drain"))
    preemption.register_drain(lambda: order.append("module_drain_1"))
    preemption.register_drain(lambda: order.append("module_drain_2"))
    try:
        gs._drain()
    finally:
        for fn in preemption.drain_hooks():
            preemption.unregister_drain(fn)
    assert order == [
        "instance_drain",
        "module_drain_1",
        "module_drain_2",
        "telemetry",
        "persist",
        "wait",
    ]


def test_graceful_shutdown_drain_hook_failure_never_blocks_persist(
    monkeypatch,
):
    from horovod_tpu import preemption
    from horovod_tpu.common import telemetry

    order = []

    class _State:
        def persist(self):
            order.append("persist")

    hub = telemetry.hub()
    monkeypatch.setattr(hub, "dump", lambda: order.append("telemetry"))

    def _bad():
        order.append("bad")
        raise RuntimeError("drain hook blew up")

    gs = preemption.GracefulShutdown(_State())
    gs.register_drain(_bad)
    gs.register_drain(lambda: order.append("good"))
    gs._drain()
    assert order == ["bad", "good", "telemetry", "persist"]


def test_graceful_shutdown_stateless_runs_drains_only(monkeypatch):
    """state=None (a serving-only worker): drains + flight recorder,
    no durable step to fail on."""
    from horovod_tpu import preemption
    from horovod_tpu.common import telemetry

    order = []
    hub = telemetry.hub()
    monkeypatch.setattr(hub, "dump", lambda: order.append("telemetry"))
    gs = preemption.GracefulShutdown(None)
    gs.register_drain(lambda: order.append("drain"))
    gs._drain()
    assert order == ["drain", "telemetry"]


def test_sigterm_runs_registered_drain_before_exit(tmp_path):
    """Real-signal half of the ordering regression: a SIGTERM'd worker
    under GracefulShutdown runs the registered drain (which records its
    evidence on disk) before exiting 143."""
    script = tmp_path / "serve_drain.py"
    marker = tmp_path / "drained.txt"
    script.write_text(
        textwrap.dedent(
            f"""
            import time
            from horovod_tpu import preemption

            def drain():
                with open({str(marker)!r}, "w") as f:
                    f.write("drained\\n")

            preemption.register_drain(drain)
            with preemption.GracefulShutdown(None):
                print("READY", flush=True)
                while True:
                    time.sleep(0.05)
            """
        )
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, str(script)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert "READY" in line, line
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 143
    assert marker.read_text().strip() == "drained"


def test_persist_bypasses_save_interval(tmp_path):
    """persist() must write the live state even when commit() would
    batch it away (save_interval>1) — the preemption grace-window
    guarantee."""
    import jax.numpy as jnp

    from horovod_tpu.checkpoint import DurableJaxState

    state = DurableJaxState(
        checkpoint_dir=str(tmp_path / "ck"),
        save_interval=100,
        params={"w": jnp.zeros(2)},
        step=0,
    )
    try:
        for _ in range(5):
            state.step += 1
            state.params = {"w": jnp.full((2,), float(state.step))}
            state.commit()
        assert state._ckpt.latest_step() is None  # batched away
        state.persist()
        state.wait_until_finished()
        assert state._ckpt.latest_step() is not None

        fresh = DurableJaxState(
            checkpoint_dir=str(tmp_path / "ck"),
            save_interval=100,
            params={"w": jnp.zeros(2)},
            step=0,
        )
        try:
            assert fresh.resume_latest()
            assert fresh.step == 5
            np.testing.assert_allclose(np.asarray(fresh.params["w"]), 5.0)
        finally:
            fresh.close()
    finally:
        state.close()


@pytest.mark.slow
def test_sigterm_produces_resumable_checkpoint(tmp_path):
    """Kill a training process mid-run; its GracefulShutdown must leave
    a durable checkpoint a fresh process resumes from."""
    ckdir = str(tmp_path / "ck")
    script = tmp_path / "train.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import sys, time
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import horovod_tpu as hvd
            from horovod_tpu.checkpoint import DurableJaxState
            from horovod_tpu.preemption import GracefulShutdown

            hvd.init()
            # save_interval=3: commit() alone would skip most durable
            # writes — the SIGTERM path must persist() unconditionally.
            state = DurableJaxState(
                checkpoint_dir={ckdir!r},
                save_interval=3,
                params={{"w": jnp.zeros(4)}},
                step=0,
            )
            with GracefulShutdown(state):
                print("READY", flush=True)
                while True:
                    state.step += 1
                    state.params = {{
                        "w": jnp.full((4,), float(state.step))
                    }}
                    state.commit()
                    time.sleep(0.05)
            """
        )
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # stderr to a file: this test is timing-sensitive under host load
    # (it failed once in a full-suite run concurrent with a TPU bench,
    # passing 5/5 in isolation) — keep the child's traceback when it
    # recurs instead of discarding the only evidence.
    errfile = tmp_path / "train.err"

    def child_err():
        return errfile.read_text()[-2000:]

    with open(errfile, "w") as errf:
        proc = subprocess.Popen(
            [sys.executable, str(script)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=errf,
            text=True,
        )
        try:
            line = proc.stdout.readline()
            assert "READY" in line, (
                f"first line {line!r}; child stderr:\n{child_err()}"
            )
            time.sleep(1.0)  # let some steps elapse
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=120)
            assert rc == 143, f"rc={rc}; child stderr:\n{child_err()}"
        finally:
            if proc.poll() is None:
                proc.kill()

    # Fresh "restarted" process state resumes from the durable commit.
    import jax
    import jax.numpy as jnp

    from horovod_tpu.checkpoint import DurableJaxState

    fresh = DurableJaxState(
        checkpoint_dir=ckdir, params={"w": jnp.zeros(4)}, step=0
    )
    try:
        assert fresh.resume_latest(), (
            f"no durable checkpoint; child stderr:\n{child_err()}"
        )
        assert fresh.step > 0
        # SIGTERM may land between the step increment and the params
        # write, so the persisted pair can legitimately be off by one.
        w = float(np.asarray(fresh.params["w"])[0])
        assert abs(w - fresh.step) <= 1.0, (
            f"w={w} step={fresh.step}; child stderr:\n{child_err()}"
        )
    finally:
        fresh.close()
