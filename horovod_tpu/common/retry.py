"""Unified retry/backoff policy for every cross-host hop.

The control plane's transports — the rendezvous KV client
(runner/rendezvous.py), the signed RPC client (runner/service.py), the
elastic worker's heartbeat loop, the driver's discovery probe — were
each a single attempt end to end: one flaky socket anywhere killed the
hop, and the hop's caller decided ad hoc whether that killed the job.
This module centralizes the decision the reference leaves to Gloo/MPI
timeouts (ref: horovod/runner/util/network.py connect retry loops +
GLOO timeout plumbing [V] — SURVEY.md §2.5): one :class:`RetryPolicy`
object per call-site, configured by the ``HOROVOD_RETRY_*`` env knobs,
with

* jittered exponential backoff between attempts,
* a per-attempt timeout hint (for the underlying socket/urlopen) and an
  overall deadline across attempts,
* retryable-exception classification (transport errors and 5xx retry;
  auth failures and 4xx never do),
* per-site ``retry.*`` counters through the metrics registry, so every
  absorbed flake is visible on ``/metrics`` as ``hvd_retry_*`` and in
  the flight-recorder StepStats deltas, and
* a per-peer circuit breaker: after N *consecutive* exhausted retry
  rounds against one peer the circuit opens and calls fail fast with
  :class:`CircuitOpenError` for a cooldown window, so a dead peer costs
  one error, not ``attempts x backoff`` of gang stall per touch.

Deliberately importable before ``hvd.init()`` (the rendezvous client
runs during bootstrap): configuration comes straight from the
environment via :meth:`RetryPolicy.from_env`, mirrored by the
``retry_*`` fields on :class:`~horovod_tpu.common.config.Config`.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

from .config import (
    DEFAULT_RETRY_ATTEMPTS as DEFAULT_ATTEMPTS,
    DEFAULT_RETRY_BACKOFF_MAX_MS as DEFAULT_BACKOFF_MAX_MS,
    DEFAULT_RETRY_BACKOFF_MS as DEFAULT_BACKOFF_MS,
    DEFAULT_RETRY_CIRCUIT_COOLDOWN_S as DEFAULT_CIRCUIT_COOLDOWN_S,
    DEFAULT_RETRY_CIRCUIT_THRESHOLD as DEFAULT_CIRCUIT_THRESHOLD,
    DEFAULT_RETRY_DEADLINE_S as DEFAULT_DEADLINE_S,
    DEFAULT_RETRY_ATTEMPT_TIMEOUT_S as DEFAULT_ATTEMPT_TIMEOUT_S,
    _env_float,
    _env_int,
)
from .logging import get_logger

_log = get_logger("retry")
# the fraction of each backoff delay randomized away (+/-): decorrelates
# a gang of workers hammering one recovering endpoint
DEFAULT_JITTER = 0.25


class RetryError(ConnectionError):
    """Every attempt failed (retryable each time) — the hop is down.

    Subclasses ``ConnectionError`` so existing ``except OSError`` /
    ``except ConnectionError`` sites treat an exhausted retry round
    exactly like the single-attempt failure they already handled.
    ``__cause__`` carries the last underlying exception."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: {attempts} attempt(s) exhausted; last error: "
            f"{type(last).__name__}: {last}"
        )
        self.site = site
        self.attempts = attempts
        self.last = last


class CircuitOpenError(ConnectionError):
    """The per-peer circuit is open: recent rounds against this peer all
    exhausted their retries, so the policy fails fast instead of
    stalling the caller for another full backoff ladder."""

    def __init__(self, site: str, peer: str, until: float):
        super().__init__(
            f"{site}: circuit open for peer {peer!r} "
            f"(~{max(until - time.monotonic(), 0.0):.1f}s until half-open)"
        )
        self.site = site
        self.peer = peer


def default_retryable(exc: BaseException) -> bool:
    """Transport-shaped failures retry; protocol/auth failures don't.

    * anything flagging itself ``retryable = True`` (the chaos layer's
      injected 5xx does) -> retry
    * ``urllib.error.HTTPError`` -> retry only 429/5xx (a 404 is the KV
      polling miss, a 403 is an HMAC mismatch — retrying can't help)
    * ``PermissionError`` (bad RPC digest) -> never
    * ``ConnectionError`` / ``TimeoutError`` / other ``OSError`` -> retry
    """
    if getattr(exc, "retryable", False):
        return True
    try:
        from urllib.error import HTTPError
    except ImportError:  # pragma: no cover
        HTTPError = ()  # type: ignore[assignment]
    if isinstance(exc, HTTPError):
        return exc.code == 429 or 500 <= exc.code <= 599
    if isinstance(exc, PermissionError):
        return False
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


class _Breaker:
    """Consecutive-exhaustion counter + open-until stamp for one peer."""

    __slots__ = ("failures", "open_until", "half_open")

    def __init__(self) -> None:
        self.failures = 0
        self.open_until = 0.0
        self.half_open = False


# process-wide breaker table: the breaker must outlive the (often
# per-call) RetryPolicy objects, or a dead peer would never accumulate
# consecutive failures
_breakers: Dict[Tuple[str, str], _Breaker] = {}
_breakers_lock = threading.Lock()


def _reset_breakers() -> None:
    """Test hook: forget all circuit state."""
    with _breakers_lock:
        _breakers.clear()


def backoff_delays(
    initial_s: float,
    cap_s: float,
    jitter: float = DEFAULT_JITTER,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Infinite jittered-doubling delay sequence — the shared backoff
    shape for both attempt retries and polling waits (the rendezvous
    ``wait`` loop uses this directly with cap ~1s)."""
    rng = rng or random
    delay = max(float(initial_s), 0.0)
    cap_s = max(float(cap_s), 0.001)
    while True:
        base = min(delay, cap_s)
        if jitter > 0:
            base *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        yield max(base, 0.0)
        delay = min(delay * 2.0 if delay > 0 else cap_s / 8, cap_s)


class RetryPolicy:
    """Jittered-exponential retry with deadline, classification,
    metrics, and a per-peer circuit breaker.

    One policy per *site* (a short dotted name like ``"kv.request"``);
    counters are published as ``retry.<site>.*`` plus process-wide
    ``retry.*_total`` aggregates the flight recorder snapshots per step.
    """

    def __init__(
        self,
        site: str,
        attempts: int = DEFAULT_ATTEMPTS,
        backoff_ms: float = DEFAULT_BACKOFF_MS,
        backoff_max_ms: float = DEFAULT_BACKOFF_MAX_MS,
        deadline_s: float = DEFAULT_DEADLINE_S,
        attempt_timeout_s: float = DEFAULT_ATTEMPT_TIMEOUT_S,
        retryable: Callable[[BaseException], bool] = default_retryable,
        circuit_threshold: int = DEFAULT_CIRCUIT_THRESHOLD,
        circuit_cooldown_s: float = DEFAULT_CIRCUIT_COOLDOWN_S,
        jitter: float = DEFAULT_JITTER,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.site = site
        self.attempts = max(int(attempts), 1)
        self.backoff_s = max(float(backoff_ms), 0.0) / 1e3
        self.backoff_max_s = max(float(backoff_max_ms), 1.0) / 1e3
        self.deadline_s = float(deadline_s)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.is_retryable = retryable
        self.circuit_threshold = max(int(circuit_threshold), 0)
        self.circuit_cooldown_s = max(float(circuit_cooldown_s), 0.0)
        self.jitter = float(jitter)
        # per-process decorrelation: two workers with identical configs
        # must not march their backoffs in lockstep against one server
        self._rng = rng or random.Random(f"{site}:{os.getpid()}")
        self._sleep = sleep

    @classmethod
    def from_env(cls, site: str, **overrides) -> "RetryPolicy":
        """Build from ``HOROVOD_RETRY_*`` (usable before hvd.init() —
        policies guard the rendezvous bootstrap itself). Shares the
        defaults AND the parsers with ``Config``'s ``retry_*`` typed
        mirror, so the two surfaces cannot drift. Explicit keyword
        overrides win over env."""
        kw = dict(
            attempts=_env_int("HOROVOD_RETRY_ATTEMPTS", DEFAULT_ATTEMPTS),
            backoff_ms=_env_float(
                "HOROVOD_RETRY_BACKOFF_MS", DEFAULT_BACKOFF_MS
            ),
            backoff_max_ms=_env_float(
                "HOROVOD_RETRY_BACKOFF_MAX_MS", DEFAULT_BACKOFF_MAX_MS
            ),
            deadline_s=_env_float(
                "HOROVOD_RETRY_DEADLINE_S", DEFAULT_DEADLINE_S
            ),
            attempt_timeout_s=_env_float(
                "HOROVOD_RETRY_ATTEMPT_TIMEOUT_S", DEFAULT_ATTEMPT_TIMEOUT_S
            ),
            circuit_threshold=_env_int(
                "HOROVOD_RETRY_CIRCUIT_THRESHOLD", DEFAULT_CIRCUIT_THRESHOLD
            ),
            circuit_cooldown_s=_env_float(
                "HOROVOD_RETRY_CIRCUIT_COOLDOWN_S", DEFAULT_CIRCUIT_COOLDOWN_S
            ),
        )
        kw.update(overrides)
        return cls(site, **kw)

    # ------------------------------------------------------------ metrics

    def _count(self, which: str, inc: float = 1.0) -> None:
        from .metrics import registry as _metrics

        _metrics.counter(f"retry.{self.site}.{which}", inc)
        _metrics.counter(f"retry.{which}_total", inc)

    # ----------------------------------------------------- circuit breaker

    def _breaker(self, peer: str) -> _Breaker:
        key = (self.site, peer)
        with _breakers_lock:
            b = _breakers.get(key)
            if b is None:
                b = _breakers[key] = _Breaker()
            return b

    def _check_circuit(self, peer: Optional[str]) -> None:
        if peer is None or self.circuit_threshold <= 0:
            return
        b = self._breaker(peer)
        now = time.monotonic()
        with _breakers_lock:
            if b.failures < self.circuit_threshold:
                return
            if now < b.open_until:
                pass  # still open -> raise below (outside the lock)
            elif not b.half_open:
                # cooldown elapsed: let exactly one probe round through
                b.half_open = True
                return
            else:
                return  # a probe is already in flight; let callers race
        self._count("circuit_open")
        raise CircuitOpenError(self.site, peer, b.open_until)

    def _record_outcome(self, peer: Optional[str], ok: bool) -> None:
        if peer is None or self.circuit_threshold <= 0:
            return
        b = self._breaker(peer)
        with _breakers_lock:
            if ok:
                b.failures = 0
                b.open_until = 0.0
                b.half_open = False
                return
            b.failures += 1
            b.half_open = False
            if b.failures >= self.circuit_threshold:
                b.open_until = time.monotonic() + self.circuit_cooldown_s
        if b.failures == self.circuit_threshold:
            _log.warning(
                "%s: circuit OPEN for peer %s after %d consecutive "
                "exhausted rounds (cooldown %.1fs)",
                self.site, peer, b.failures, self.circuit_cooldown_s,
            )

    def circuit_state(self, peer: str) -> str:
        """'closed' | 'open' | 'half_open' — observability/test surface."""
        b = self._breaker(peer)
        with _breakers_lock:
            if b.failures < self.circuit_threshold:
                return "closed"
            if time.monotonic() < b.open_until and not b.half_open:
                return "open"
            return "half_open" if b.half_open else "open"

    # ---------------------------------------------------------------- call

    def call(self, fn: Callable, *args, peer: Optional[str] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under the policy.

        Retries when ``is_retryable(exc)``; sleeps the jittered backoff
        between attempts; stops early when the overall deadline would be
        crossed; raises :class:`RetryError` (chained to the last
        failure) on exhaustion, or the original exception immediately
        when it isn't retryable. With ``peer`` set, consults/updates the
        per-peer circuit breaker. ``fn`` must be safe to re-run — every
        wired site is an idempotent GET/PUT/notify."""
        self._check_circuit(peer)
        deadline = (
            time.monotonic() + self.deadline_s
            if self.deadline_s > 0
            else None
        )
        delays = backoff_delays(
            self.backoff_s, self.backoff_max_s, self.jitter, self._rng
        )
        last: Optional[BaseException] = None
        for attempt in range(1, self.attempts + 1):
            self._count("attempts")
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self.is_retryable(e):
                    # surfaces immediately and does NOT move the
                    # breaker: an auth/4xx failure is a protocol
                    # problem, not evidence the peer is dead — only
                    # exhausted rounds open the circuit (success still
                    # closes it)
                    raise
                last = e
                if attempt >= self.attempts:
                    break
                delay = next(delays)
                if deadline is not None and (
                    time.monotonic() + delay >= deadline
                ):
                    _log.debug(
                        "%s: deadline would be crossed; stopping after "
                        "attempt %d", self.site, attempt,
                    )
                    break
                self._count("retries")
                _log.debug(
                    "%s: attempt %d/%d failed (%s: %s); retrying in "
                    "%.0fms", self.site, attempt, self.attempts,
                    type(e).__name__, e, delay * 1e3,
                )
                # trace plane: pin this retry to the hop span it is
                # running under (site + attempt + backoff) — a no-op
                # thread-local read when the request is untraced
                from . import tracing as _tracing

                _tracing.annotate(
                    f"retry:{self.site}#{attempt}@{delay * 1e3:.0f}ms"
                )
                self._sleep(delay)
            else:
                self._record_outcome(peer, ok=True)
                return out
        self._count("exhausted")
        self._record_outcome(peer, ok=False)
        assert last is not None
        # report the attempts that actually RAN — the deadline may have
        # stopped the round short of the configured budget
        raise RetryError(self.site, attempt, last) from last
