#!/usr/bin/env bash
# Round-5 chip work, part a: the consolidated capture roster that the
# 2026-07-31 axon outage (longest observed; outlasted round 4) left
# unlanded, re-prioritized per VERDICT.md r4 "Next round" items 1/2/8:
#   1. BERT closure (comparable-config re-runs; BASELINE config #3)
#   2. fused linear-cross-entropy A/B (the MFU>=0.60 lever)
#   3. gpt2 seq-1024 + current-default captures
#   4. fresh ResNet headline refresh (bench.py stale reprint is dated
#      2026-07-30; driver needs a stale:false round-5 artifact)
#   5. on-chip kernel smokes for the padded/GQA/window paths
#   6. padded / GQA / ViT A/B cells, allreduce, published family
# Discipline (docs/benchmarks.md + memory): skip-if-done, one attempt,
# backend-probe gate, one retry, ONE TPU process at a time, and a HOLD
# file (scripts/CHIP_HOLD) the dev session touches while running the
# full pytest suite so host CPU load never confounds a capture.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r05

echo "=== chipwork_r05a start $(date -u +%F' '%H:%M)" >&2

while pgrep -f "chipwork_r04" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce)?.py" >/dev/null 2>&1; do
  sleep 60
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}

wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}

hold_gate() {  # dev session touches scripts/CHIP_HOLD while running pytest
  while [ -e scripts/CHIP_HOLD ]; do
    echo "=== CHIP_HOLD present; waiting $(date -u +%H:%M)" >&2
    sleep 60
  done
}

run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}

cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  hold_gate
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

smoke() {  # like cap but for pass/fail scripts: keep a .txt transcript
  local name="$1"; shift
  local out="bench_results/${name}_${R}.txt"
  if [ -s "$out" ] && grep -q "ALL OK" "$out"; then
    echo "=== $name already passed, skipping" >&2
    return 0
  fi
  hold_gate
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out" 2>&1
  if grep -q "ALL OK" "$out"; then cat "$out" >&2; return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  hold_gate
  "$@" > "$out" 2>&1
  grep -q "ALL OK" "$out" && { cat "$out" >&2; return 0; }
  echo "FAILED $name twice with backend up (transcript: $out)" >&2
  return 1
}

# Gate the whole roster on the backend being up at all before the first
# claim -- a failed claim wastes its 20-30 min queue slot.
wait_backend

# -- 1. BERT closure (VERDICT Weak #1: must beat r03's 65.44/0.367 at a
#       comparable config before round 5 ends)
cap bert_large          env BENCH_MODEL=bert_large python bench_lm.py
cap bert_noremat_b16    env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py

# -- 2. fused linear-cross-entropy A/B (VERDICT item 2: MFU>=0.60 or
#       a profile-backed refutation)
cap gpt2_default        env BENCH_MODEL=gpt2_medium python bench_lm.py
cap gpt2_fxent          env BENCH_MODEL=gpt2_medium BENCH_FUSED_XENT=1 python bench_lm.py
cap gpt2_noremat_b16    env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
cap gpt2_best_fxent     env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FUSED_XENT=1 python bench_lm.py
cap bert_fxent          env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FUSED_XENT=1 python bench_lm.py

# -- 3. long-context cells (VERDICT item 8 start; more in part b)
cap gpt2_seq1024        env BENCH_MODEL=gpt2_medium BENCH_BATCH=4 BENCH_SEQ=1024 python bench_lm.py

# -- 4. fresh ResNet headline so BENCH_r05 is stale:false
cap resnet50_s2d_clean  env BENCH_INNER=1 BENCH_STEM=space_to_depth python bench.py
cap resnet50_clean      env BENCH_INNER=1 python bench.py

# -- 5. on-chip kernel smokes (padded SMEM lens spec, GQA, window)
smoke flash_padded_smoke python scripts/smoke_flash_padded.py
smoke flash_gqa_window_smoke python scripts/smoke_flash_gqa_window.py

# -- 6. remaining A/B cells + allreduce + published family
cap gpt2_padded         env BENCH_MODEL=gpt2_medium BENCH_PADDED=1 python bench_lm.py
cap bert_padded         env BENCH_MODEL=bert_large BENCH_PADDED=1 python bench_lm.py
cap gpt2_gqa4           env BENCH_MODEL=gpt2_medium BENCH_KV_HEADS=4 python bench_lm.py
cap gpt2_gqa8           env BENCH_MODEL=gpt2_medium BENCH_KV_HEADS=8 python bench_lm.py
cap vit_b16_flash       env BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py
cap vit_b16_dense       env BENCH_INNER=1 BENCH_MODEL=vit_b16 BENCH_VIT_FLASHPAD=0 python bench.py
cap allreduce           python bench_allreduce.py
cap inception_v3        env BENCH_INNER=1 BENCH_MODEL=inception_v3 python bench.py
cap resnet101           env BENCH_INNER=1 BENCH_MODEL=resnet101 python bench.py
cap vgg16               env BENCH_INNER=1 BENCH_MODEL=vgg16 BENCH_BATCH=128 python bench.py

echo "=== chipwork_r05a complete $(date -u +%F' '%H:%M)" >&2
