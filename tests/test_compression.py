"""Compression round-trips (ref: compression handling asserted inside
test_torch.py's fp16 allreduce cases [V])."""

import jax.numpy as jnp
import numpy as np

from horovod_tpu.ops.compression import Compression


def test_none_identity():
    x = jnp.asarray([1.5, 2.5])
    wire, ctx = Compression.none.compress(x)
    assert wire is x
    assert Compression.none.decompress(wire, ctx) is x


def test_fp16_roundtrip():
    x = jnp.asarray(np.linspace(-4, 4, 16, dtype=np.float32))
    wire, ctx = Compression.fp16.compress(x)
    assert wire.dtype == jnp.float16
    out = Compression.fp16.decompress(wire, ctx)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-3)


def test_bf16_roundtrip_preserves_range():
    x = jnp.asarray([1e30, -1e-30, 3.0], dtype=np.float32)
    wire, ctx = Compression.bf16.compress(x)
    assert wire.dtype == jnp.bfloat16
    out = Compression.bf16.decompress(wire, ctx)
    assert out.dtype == jnp.float32
    # bf16 keeps fp32's exponent range — 1e30 survives (fp16 would inf)
    np.testing.assert_allclose(np.asarray(out)[0], 1e30, rtol=1e-2)


def test_int_passthrough():
    x = jnp.asarray([1, 2, 3], dtype=jnp.int32)
    wire, ctx = Compression.fp16.compress(x)
    assert wire.dtype == jnp.int32  # non-float left alone
    out = Compression.fp16.decompress(wire, ctx)
    assert out.dtype == jnp.int32
