// _hvd_cext: the CPython-C-API half of the framework bindings.
//
// TPU-native rebuild of the reference's native binding layer (ref:
// horovod/torch/adapter_v2.cc TorchTensor wrapping a torch storage for
// the C core with zero copies, and horovod/common/ops/
// collective_operations.cc MemcpyInFusionBuffer — SURVEY.md §2.3). On
// TPU the collective data plane is XLA's, so the adapter's surviving
// job is HOST staging: framework tensors expose their bytes through the
// buffer protocol and this module copies them into / out of one
// contiguous block with the GIL released — no ctypes pointer
// marshalling, no per-tensor Python allocations. Consumers: the torch
// shim's elastic TorchState commit snapshot and _native/loader.py's
// pack/unpack fast path.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <vector>

namespace {

// Acquire C-contiguous buffer views of every element of `seq_obj`.
// On failure releases everything acquired so far and returns false with
// a Python error set.
bool collect_buffers(PyObject* fast_seq, int flags,
                     std::vector<Py_buffer>* out) {
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast_seq);
  out->reserve(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast_seq, i);
    Py_buffer view;
    if (PyObject_GetBuffer(item, &view, flags) != 0) {
      for (Py_buffer& b : *out) PyBuffer_Release(&b);
      out->clear();
      return false;
    }
    out->push_back(view);
  }
  return true;
}

void release_all(std::vector<Py_buffer>* views) {
  for (Py_buffer& b : *views) PyBuffer_Release(&b);
  views->clear();
}

PyObject* pack_into(PyObject*, PyObject* args) {
  PyObject* dst_obj;
  PyObject* srcs_obj;
  if (!PyArg_ParseTuple(args, "OO:pack_into", &dst_obj, &srcs_obj)) {
    return nullptr;
  }
  Py_buffer dst;
  if (PyObject_GetBuffer(dst_obj, &dst,
                         PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) != 0) {
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(srcs_obj, "srcs must be a sequence");
  if (seq == nullptr) {
    PyBuffer_Release(&dst);
    return nullptr;
  }
  std::vector<Py_buffer> srcs;
  if (!collect_buffers(seq, PyBUF_C_CONTIGUOUS, &srcs)) {
    Py_DECREF(seq);
    PyBuffer_Release(&dst);
    return nullptr;
  }
  Py_ssize_t total = 0;
  for (const Py_buffer& b : srcs) total += b.len;
  if (total > dst.len) {
    PyErr_Format(PyExc_ValueError,
                 "pack_into: dst holds %zd bytes, sources total %zd",
                 dst.len, total);
    release_all(&srcs);
    Py_DECREF(seq);
    PyBuffer_Release(&dst);
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  char* out = static_cast<char*>(dst.buf);
  Py_ssize_t off = 0;
  for (const Py_buffer& b : srcs) {
    if (b.len > 0) std::memcpy(out + off, b.buf, static_cast<size_t>(b.len));
    off += b.len;
  }
  Py_END_ALLOW_THREADS
  release_all(&srcs);
  Py_DECREF(seq);
  PyBuffer_Release(&dst);
  return PyLong_FromSsize_t(total);
}

PyObject* unpack_into(PyObject*, PyObject* args) {
  PyObject* src_obj;
  PyObject* dsts_obj;
  if (!PyArg_ParseTuple(args, "OO:unpack_into", &src_obj, &dsts_obj)) {
    return nullptr;
  }
  Py_buffer src;
  if (PyObject_GetBuffer(src_obj, &src, PyBUF_C_CONTIGUOUS) != 0) {
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(dsts_obj, "dsts must be a sequence");
  if (seq == nullptr) {
    PyBuffer_Release(&src);
    return nullptr;
  }
  std::vector<Py_buffer> dsts;
  if (!collect_buffers(seq, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS, &dsts)) {
    Py_DECREF(seq);
    PyBuffer_Release(&src);
    return nullptr;
  }
  Py_ssize_t total = 0;
  for (const Py_buffer& b : dsts) total += b.len;
  if (total > src.len) {
    PyErr_Format(PyExc_ValueError,
                 "unpack_into: src holds %zd bytes, destinations need %zd",
                 src.len, total);
    release_all(&dsts);
    Py_DECREF(seq);
    PyBuffer_Release(&src);
    return nullptr;
  }
  Py_BEGIN_ALLOW_THREADS
  const char* in = static_cast<const char*>(src.buf);
  Py_ssize_t off = 0;
  for (const Py_buffer& b : dsts) {
    if (b.len > 0) std::memcpy(b.buf, in + off, static_cast<size_t>(b.len));
    off += b.len;
  }
  Py_END_ALLOW_THREADS
  release_all(&dsts);
  Py_DECREF(seq);
  PyBuffer_Release(&src);
  return PyLong_FromSsize_t(total);
}

PyMethodDef methods[] = {
    {"pack_into", pack_into, METH_VARARGS,
     "pack_into(dst, srcs) -> int\n\n"
     "Copy the raw bytes of each buffer-protocol object in `srcs`,\n"
     "in order, into the writable C-contiguous buffer `dst` (GIL\n"
     "released during the copies). Returns total bytes written.\n"
     "Raises ValueError when `dst` is too small."},
    {"unpack_into", unpack_into, METH_VARARGS,
     "unpack_into(src, dsts) -> int\n\n"
     "Scatter consecutive byte ranges of `src` into the writable\n"
     "buffers `dsts` (each filled to its own length, GIL released).\n"
     "Returns total bytes read. Raises ValueError when `src` is\n"
     "shorter than the destinations' total."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT,
    "_hvd_cext",
    "Buffer-protocol host staging: the CPython-extension native half\n"
    "of the framework bindings (see csrc/cext.cc header).",
    -1,
    methods,
    nullptr,
    nullptr,
    nullptr,
    nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__hvd_cext(void) { return PyModule_Create(&module); }
