"""``import horovod_tpu.keras as hvd`` — the standalone-Keras binding
(ref: horovod/keras/__init__.py [V]).

Upstream keeps two Keras modules for the multi-backend Keras era; since
standalone Keras is tf.keras's successor with the same training-loop
API, this is one surface: everything re-exports from
:mod:`horovod_tpu.tensorflow.keras`.
"""

from __future__ import annotations

from ..tensorflow.keras import (  # noqa: F401
    Adasum,
    Average,
    DistributedOptimizer,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    broadcast,
    broadcast_variables,
    callbacks,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    load_model,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)


def __getattr__(name):
    import horovod_tpu.tensorflow.keras as _k

    return getattr(_k, name)
