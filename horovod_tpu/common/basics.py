"""Global runtime state and the init/shutdown lifecycle.

TPU-native re-design of the reference's C-API bootstrap + global state
(ref: horovod/common/operations.cc `horovod_init`/`InitializeHorovodOnce` +
horovod/common/global_state.h `HorovodGlobalState` + horovod/common/basics.py
`HorovodBasics` [V], SURVEY.md §2.1/§3.1).

What is deliberately *absent* relative to the reference: the background
coordination thread and the Request/Response negotiation protocol. On TPU,
XLA's static schedule plays that role for traced code (SURVEY.md §5.8); the
eager path batches through a fusion manager (ops/fusion.py) driven from the
dispatching thread, so no dedicated coordinator thread is needed — dispatch
order is identical on every process because eager dispatch happens on the
single controller.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from . import config as config_mod
from . import topology as topo_mod
from .process_sets import ProcessSet, ProcessSetTable


class HorovodInternalError(RuntimeError):
    """A collective failed (peer/slice died). Elastic catches this
    (ref: horovod/common/exceptions [V], surfaced to hvd.elastic.run)."""


class HostsUpdatedInterrupt(Exception):
    """Cluster membership changed; current state is still good
    (ref: horovod/common/elastic.py [V])."""


class NotInitializedError(RuntimeError):
    def __init__(self):
        super().__init__(
            "horovod_tpu has not been initialized; call hvd.init() first."
        )


class _GlobalState:
    """Singleton mirroring HorovodGlobalState (global_state.h [V])."""

    def __init__(self):
        self.lock = threading.Lock()
        self.initialized = False
        self.config: Optional[config_mod.Config] = None
        self.topology: Optional[topo_mod.Topology] = None
        self.mesh = None
        self.process_set_table: Optional[ProcessSetTable] = None
        self.fusion = None  # FusionManager, attached by ops.eager on init
        self.timeline = None  # Timeline, attached when HOROVOD_TIMELINE set
        self.traced_timeline = None  # TracedTimeline (jax.profiler wrapper)
        self.parameter_manager = None  # autotune, attached when enabled
        self.stall_inspector = None
        self.telemetry_server = None  # MetricsServer (HOROVOD_METRICS_PORT)


_state = _GlobalState()


def _maybe_init_jax_distributed(cfg: config_mod.Config) -> None:
    """Join the jax.distributed coordination service when the runner
    exported coordinator env (HOROVOD_COORDINATOR_ADDR/PORT +
    HOROVOD_NUM_PROCESSES/PROCESS_ID).

    This is the TPU-native replacement for the reference's MPI_Init /
    Gloo-rendezvous bootstrap inside BackgroundThreadLoop (ref:
    horovod/common/operations.cc §3.1 [V]): rank-0's host runs the
    coordination service; everyone else dials in. Must happen before the
    first jax.devices() call, which is why it lives at the top of init().
    """
    if not cfg.coordinator_addr or not cfg.num_processes:
        return
    if cfg.num_processes <= 1:
        return
    import jax

    if jax.distributed.is_initialized():
        return  # already joined (e.g. TPU-VM auto-bootstrap)
    jax.distributed.initialize(
        coordinator_address=f"{cfg.coordinator_addr}:{cfg.coordinator_port}",
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )


def _require_init() -> _GlobalState:
    if not _state.initialized:
        raise NotInitializedError()
    return _state


def state() -> _GlobalState:
    return _state


def init(process_sets: Optional[Sequence[ProcessSet]] = None) -> None:
    """Initialize the runtime: read config, discover topology, build the
    world mesh, register process sets, start aux subsystems.

    Idempotent like the reference's InitializeHorovodOnce
    (operations.cc [V]). Unlike the reference there is no thread to spawn:
    collective scheduling is XLA's job.
    """
    with _state.lock:
        if _state.initialized:
            return
        cfg = config_mod.Config.from_env()
        # Logging first so every subsystem below starts up observable
        # (ref: logging.cc — level/timestamp read once at init [V]).
        from . import logging as hvd_logging

        log = hvd_logging.configure_from_init(
            cfg.log_level, cfg.log_timestamp
        )
        from .metrics import registry as _metrics

        _metrics.configure_export()  # HOROVOD_METRICS_FILE, if set
        _maybe_init_jax_distributed(cfg)
        topology = topo_mod.discover(cfg)
        if cfg.rendezvous_addr:
            # Same-version gang guard (the launch driver's probe in the
            # reference, driver_service.py [V]); mismatch raises, any
            # rendezvous trouble only warns.
            from ..runner.rendezvous import check_version_consistency

            check_version_consistency(cfg, topology, log)
        _state.config = cfg
        _state.topology = topology
        _state.mesh = topology.world_mesh()
        _state.process_set_table = ProcessSetTable(topology.size)
        if process_sets:
            for ps in process_sets:
                _state.process_set_table.register(ps)

        # Aux subsystems — imported lazily to keep the init dependency graph
        # one-directional (they all depend on basics).
        from ..ops.fusion import FusionManager

        _state.fusion = FusionManager(
            mesh=_state.mesh,
            threshold_bytes=cfg.fusion_threshold_bytes,
            cycle_time_ms=cfg.cycle_time_ms,
            cache_capacity=cfg.cache_capacity,
            injit_pack=cfg.fusion_injit,
            bucketing=cfg.fusion_buckets,
            donate=cfg.fusion_donate,
            promote_after=cfg.fusion_promote_after,
            wire=cfg.fusion_wire,
            wire_block=cfg.fusion_wire_block,
            wire_hier=cfg.fusion_wire_hier,
            wire_min_bytes=cfg.fusion_wire_min_bytes,
            guard=cfg.guard,
        )
        if cfg.timeline:
            from .timeline import Timeline

            _state.timeline = Timeline(cfg.timeline, mark_cycles=cfg.timeline_mark_cycles)
            _state.fusion.timeline = _state.timeline
        if not cfg.stall_check_disable:
            from .stall_inspector import StallInspector

            _state.stall_inspector = StallInspector(
                warning_seconds=cfg.stall_warning_seconds,
                shutdown_seconds=cfg.stall_shutdown_seconds,
                straggler_factor=cfg.straggler_factor,
            )
            _state.fusion.stall_inspector = _state.stall_inspector
        # Telemetry hub (flight recorder) + optional live scrape
        # endpoint. The hub is process-wide and outlives init/shutdown
        # cycles (the flight recorder must survive a teardown to be a
        # post-mortem tool); init only refreshes its knobs and wires
        # the current timeline/inspector into it.
        from . import telemetry as telemetry_mod

        _hub = telemetry_mod.hub()
        _hub.configure(
            capacity=cfg.telemetry_steps,
            flight_path=cfg.flight_recorder,
        )
        _hub.timeline = _state.timeline
        _hub.stall_inspector = _state.stall_inspector
        if cfg.metrics_port:
            _state.telemetry_server = telemetry_mod.MetricsServer(
                port=cfg.metrics_port
            )
            _state.telemetry_server.start()
        if cfg.autotune:
            from .autotune import ParameterManager

            _state.parameter_manager = ParameterManager.from_config(cfg)
            _state.fusion.parameter_manager = _state.parameter_manager
        _state.initialized = True
        log.info(
            "initialized: world=%d local=%d platform=%s fusion=%dB "
            "cycle=%.1fms cache=%d",
            topology.size,
            topology.local_size,
            getattr(topology.devices[0], "platform", "?"),
            cfg.fusion_threshold_bytes,
            cfg.cycle_time_ms,
            cfg.cache_capacity,
        )


def shutdown() -> None:
    """Tear down (ref: horovod_shutdown in operations.cc [V])."""
    with _state.lock:
        if not _state.initialized:
            return
        if _state.fusion is not None:
            _state.fusion.flush()
        if _state.timeline is not None:
            _state.timeline.close()
        if _state.traced_timeline is not None:
            _state.traced_timeline.close()
        if _state.telemetry_server is not None:
            _state.telemetry_server.stop()
        from . import telemetry as telemetry_mod

        _hub = telemetry_mod.hub()
        _hub.timeline = None
        _hub.stall_inspector = None
        try:
            # the ring survives shutdown (post-mortem tool), but a
            # clean teardown is a natural dump point for the recorder
            _hub.dump()
        except OSError:
            pass
        _state.initialized = False
        _state.config = None
        _state.topology = None
        _state.mesh = None
        _state.process_set_table = None
        _state.fusion = None
        _state.timeline = None
        _state.traced_timeline = None
        _state.parameter_manager = None
        _state.stall_inspector = None
        _state.telemetry_server = None


def is_initialized() -> bool:
    return _state.initialized


# --- rank/size queries (ref: HorovodBasics in horovod/common/basics.py [V]) ---


def size() -> int:
    return _require_init().topology.size


def rank() -> int:
    return _require_init().topology.rank


def local_size() -> int:
    return _require_init().topology.local_size


def local_rank() -> int:
    return _require_init().topology.local_rank


def cross_size() -> int:
    return _require_init().topology.cross_size


def cross_rank() -> int:
    return _require_init().topology.cross_rank


def mesh():
    return _require_init().mesh


def topology() -> topo_mod.Topology:
    return _require_init().topology


def live_config() -> config_mod.Config:
    """The initialized runtime's config snapshot when there is one,
    else a fresh env parse — the resolution every config-deferring
    default (overlap buckets, guard, audit cadence) shares."""
    if _state.initialized and _state.config is not None:
        return _state.config
    return config_mod.Config.from_env()


def get_config() -> config_mod.Config:
    return _require_init().config


def is_homogeneous() -> bool:
    """True when every host drives the same number of chips
    (ref: horovod_is_homogeneous [V]; always true on a TPU slice)."""
    st = _require_init()
    return st.topology.size == st.topology.cross_size * st.topology.local_size


# --- build-capability predicates, API parity with basics.py [V] ---


def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def tpu_enabled() -> bool:
    return True


def mpi_threads_supported() -> bool:
    return False


# --- process-set API (ref: horovod/common/process_sets.py [V]) ---


def add_process_set(ranks: Sequence[int]) -> ProcessSet:
    st = _require_init()
    ps = ranks if isinstance(ranks, ProcessSet) else ProcessSet(ranks)
    return st.process_set_table.register(ps)


def remove_process_set(ps: ProcessSet) -> None:
    _require_init().process_set_table.remove(ps)


def get_process_set_ids() -> Sequence[int]:
    return _require_init().process_set_table.ids()


def get_process_set(process_set_id: int) -> ProcessSet:
    return _require_init().process_set_table.get(process_set_id)


def global_process_set() -> ProcessSet:
    return _require_init().process_set_table.global_set
