"""Multi-axis-parallel transformer LM training.

This goes beyond the reference's capability surface (Horovod is
data-parallel only — SURVEY.md §2.6): one mesh carrying dp x pp x ep x
sp x tp simultaneously, with ring attention for the sequence axis,
GPipe-style microbatching for the pipeline axis, and expert-parallel
MoE over all_to_all — the collective the reference ships as a bare
primitive [V] is here the backbone of a parallelism strategy.

Run (8-way CPU simulation):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/transformer_lm.py --dp 2 --sp 2 --tp 2
Run (TPU pod): choose axes to match the slice.
"""

import argparse
import os

import jax

# The sandbox's sitecustomize can force-select a TPU platform; honor an
# explicit JAX_PLATFORMS request at the config level (see tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np

from horovod_tpu.parallel import MeshSpec
from horovod_tpu.parallel.transformer import (
    ParallelTransformerConfig,
    make_sharded_params,
    make_train_step,
)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dp", type=int, default=2)
    parser.add_argument("--pp", type=int, default=1)
    parser.add_argument("--ep", type=int, default=1)
    parser.add_argument("--sp", type=int, default=2)
    parser.add_argument("--tp", type=int, default=2)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--batch-per-dp", type=int, default=4)
    args = parser.parse_args()

    spec = MeshSpec(
        dp=args.dp, pp=args.pp, ep=args.ep, sp=args.sp, tp=args.tp
    )
    if spec.size != len(jax.devices()):
        raise SystemExit(
            f"mesh {spec} needs {spec.size} devices; "
            f"{len(jax.devices())} visible"
        )
    mesh = spec.build()

    cfg = ParallelTransformerConfig(
        vocab_size=512,
        num_layers=2 * max(args.pp, 1),
        d_model=128,
        num_heads=max(4, args.tp),
        d_ff=256,
        max_len=args.seq_len,
        n_experts=2 * max(args.ep, 1),
        n_microbatches=2,
        learning_rate=0.1,
    )
    params = make_sharded_params(cfg, mesh, jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh)

    rng = np.random.default_rng(0)
    global_batch = args.batch_per_dp * args.dp * max(args.ep, 1)
    # A learnable synthetic language: next token = (token + 1) % K.
    base = rng.integers(0, cfg.vocab_size - 1, size=(global_batch, 1))
    seq = (base + np.arange(args.seq_len + 1)[None, :]) % cfg.vocab_size
    tokens = jnp.asarray(seq[:, :-1], jnp.int32)
    labels = jnp.asarray(seq[:, 1:], jnp.int32)

    losses = []
    for i in range(args.steps):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
        print(f"step {i}: loss {losses[-1]:.4f}")
    if losses[-1] < losses[0]:
        print("loss decreased — parallel training works")
    else:
        raise SystemExit("loss did not decrease")


if __name__ == "__main__":
    main()
