"""Regression tests for the code-review findings on the eager/traced core:
Adasum fusion isolation, process-set semantics on every op family, join
masks in grouped ops, autotune bootstrap, env-contract validation."""

import numpy as np
import pytest

import horovod_tpu as hvd_mod


def rank_major(fn, dtype=np.float32):
    return np.stack([np.asarray(fn(r), dtype=dtype) for r in range(8)])


def test_adasum_entries_not_cross_fused(hvd, rng):
    """Two Adasum allreduces in one cycle must equal two solo dispatches."""
    fusion = hvd_mod.common.basics.state().fusion
    fusion.cycle_time_ms = 1e6
    a = rank_major(lambda r: rng.normal(size=5))
    b = rank_major(lambda r: rng.normal(size=5))
    ha = hvd.allreduce_async(a, op=hvd_mod.Adasum, name="a")
    hb = hvd.allreduce_async(b, op=hvd_mod.Adasum, name="b")
    fused_a, fused_b = ha.wait(), hb.wait()
    solo_a = hvd.allreduce(a, op=hvd_mod.Adasum, name="a2")
    solo_b = hvd.allreduce(b, op=hvd_mod.Adasum, name="b2")
    np.testing.assert_allclose(
        np.asarray(fused_a), np.asarray(solo_a), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(fused_b), np.asarray(solo_b), rtol=1e-5
    )


def test_broadcast_process_set_nonmembers_unchanged(hvd):
    ps = hvd.add_process_set([0, 1])
    x = rank_major(lambda r: np.full((2,), float(r + 1)))
    out = hvd.broadcast(x, root_rank=0, process_set=ps)
    np.testing.assert_allclose(np.asarray(out[1]), [1.0, 1.0])
    # non-members keep their own tensor, not zeros
    np.testing.assert_allclose(np.asarray(out[5]), [6.0, 6.0])


def test_grouped_allreduce_respects_join(hvd):
    x = rank_major(lambda r: np.full((3,), float(r)))
    with hvd.join_ranks([3, 4, 5, 6, 7]):
        outs = hvd.grouped_allreduce([x])
    # average over ranks 0,1,2 only
    np.testing.assert_allclose(np.asarray(outs[0][0]), np.full(3, 1.0))


def test_allgather_process_set(hvd):
    ps = hvd.add_process_set([2, 5])
    x = rank_major(lambda r: np.full((2, 3), float(r)))
    out = hvd.allgather(x, process_set=ps)
    # members see both contributions stacked
    got = np.asarray(out[2]).reshape(4, 3)
    expected = np.concatenate([np.full((2, 3), 2.0), np.full((2, 3), 5.0)])
    np.testing.assert_allclose(got, expected)
    np.testing.assert_allclose(np.asarray(out[5]).reshape(4, 3), expected)
    # non-members receive nothing (zeros)
    np.testing.assert_allclose(np.asarray(out[0]), np.zeros_like(out[0]))


def test_alltoall_process_set(hvd):
    ps = hvd.add_process_set([0, 4])
    # 2 participants; per-rank payload dim1=4 splits into 2 blocks of 2
    x = rank_major(lambda r: np.array([r * 10.0 + j for j in range(4)]))
    out = hvd.alltoall(x, process_set=ps)
    # member 0 receives its own first block and member 4's first block
    np.testing.assert_allclose(np.asarray(out[0]), [0.0, 1.0, 40.0, 41.0])
    np.testing.assert_allclose(np.asarray(out[4]), [2.0, 3.0, 42.0, 43.0])


def test_reducescatter_process_set(hvd):
    ps = hvd.add_process_set([1, 3])
    x = rank_major(lambda r: np.arange(4.0) + r)
    out = hvd.reducescatter(x, op=hvd_mod.Sum, process_set=ps)
    # members reduce rows 1 and 3: [1,2,3,4]+[3,4,5,6] = [4,6,8,10]
    np.testing.assert_allclose(np.asarray(out[1]), [4.0, 6.0])
    np.testing.assert_allclose(np.asarray(out[3]), [8.0, 10.0])


def test_adasum_process_set_eager(hvd, rng):
    ps = hvd.add_process_set([0, 1, 2, 3])
    x = rank_major(lambda r: rng.normal(size=6))
    out = hvd.allreduce(x, op=hvd_mod.Adasum, process_set=ps)
    # members agree (to float32 collective tolerance); non-members pass
    # through exactly
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(out[3]), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(np.asarray(out[6]), x[6], rtol=1e-6)


def test_traced_gather_family_pset_divisibility_raises(hvd):
    """The traced set gather family is implemented now (masked
    full-axis collectives, round 3); what still raises is a clear
    ValueError on non-divisible block splits — not a deep XLA error."""
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import traced

    ps = hvd.add_process_set([0, 1, 2])
    mesh = hvd.mesh()
    x = rank_major(lambda r: np.ones(8))

    def run(op):
        body = jax.shard_map(
            lambda t: op(t[0], process_set=ps)[None],
            mesh=mesh,
            in_specs=P(hvd_mod.WORLD_AXIS),
            out_specs=P(hvd_mod.WORLD_AXIS),
            check_vma=False,
        )
        jax.jit(body)(x)

    for op in (traced.alltoall, traced.reducescatter):
        with pytest.raises(ValueError, match="divisible"):
            run(op)


def test_autotune_init_does_not_crash(monkeypatch):
    import horovod_tpu as hvd

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    hvd.init()
    st = hvd_mod.common.basics.state()
    assert st.parameter_manager is not None
    # drive enough flushes to move through warmup + a few samples
    x = rank_major(lambda r: np.ones(64))
    for _ in range(45):
        hvd.allreduce(x, op=hvd_mod.Sum)
    thr, cyc = st.parameter_manager.current()
    assert thr > 0 and cyc > 0
    hvd.shutdown()


def test_env_contract_mismatch_raises(monkeypatch):
    import horovod_tpu as hvd

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_SIZE", "4")  # runtime reports 8
    with pytest.raises(ValueError, match="HOROVOD_SIZE=4"):
        hvd.init()
    hvd.shutdown()


def test_env_contract_match_accepted(monkeypatch):
    import horovod_tpu as hvd

    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_SIZE", "8")
    monkeypatch.setenv("HOROVOD_RANK", "0")
    monkeypatch.setenv("HOROVOD_CROSS_SIZE", "1")
    hvd.init()
    assert hvd.size() == 8
    hvd.shutdown()


def test_traced_adasum_prescale_applied(hvd, rng):
    """prescale on traced Adasum must scale the result (adasum is
    1-homogeneous when all ranks scale identically)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops import traced

    x = rank_major(lambda r: rng.normal(size=4))
    mesh = hvd.mesh()

    def run(prescale):
        f = jax.jit(
            jax.shard_map(
                lambda t: traced.allreduce(
                    t[0], op=hvd_mod.Adasum, prescale_factor=prescale
                )[None],
                mesh=mesh,
                in_specs=P(hvd_mod.WORLD_AXIS),
                out_specs=P(hvd_mod.WORLD_AXIS),
                check_vma=False,
            )
        )
        return np.asarray(f(x))

    np.testing.assert_allclose(run(2.0), 2.0 * run(1.0), rtol=1e-5)


def test_adasum_respects_join_mask(hvd, rng):
    """Joined ranks contribute Adasum's identity (zero), so the result
    must equal Adasum over the live ranks only (round-3 review fix:
    the Adasum branch used the unmasked payload)."""
    vals = np.stack(
        [rng.normal(size=6).astype(np.float32) for _ in range(8)]
    )
    with hvd.join_ranks([2, 5]):
        out = hvd.allreduce(vals, op=hvd_mod.Adasum)
    live = np.asarray(
        [vals[r] if r not in (2, 5) else np.zeros(6, np.float32)
         for r in range(8)]
    )
    # the VHDD order over the full axis with zeroed rows is the oracle
    from horovod_tpu.ops.adasum import adasum_vhdd_host

    expected = adasum_vhdd_host(live)
    np.testing.assert_allclose(
        np.asarray(out[0]), expected, rtol=1e-4, atol=1e-5
    )


# ---- ADVICE r3 regressions -------------------------------------------------


def test_alltoall_member_splits_row_none_is_clear_error(hvd):
    """A member rank whose splits row is None must get a ValueError
    naming the rank, not a TypeError from len(None) (ADVICE r3)."""
    ps = hvd.add_process_set([0, 2])
    try:
        x = rank_major(lambda r: np.arange(4) + r)
        splits = [[1, 3], None, None, None, None, None, None, None]
        with pytest.raises(ValueError, match="member rank 2"):
            hvd.alltoall(x, splits=splits, process_set=ps)
    finally:
        hvd.remove_process_set(ps)


def test_alltoall_rejects_extra_splits_rows(hvd):
    """len(splits) > world was silently accepted (ADVICE r3)."""
    x = rank_major(lambda r: np.arange(8) + r)
    splits = [[1] * 8] * 9  # 9 rows on an 8-rank world
    with pytest.raises(ValueError, match="exactly one row per WORLD"):
        hvd.alltoall(x, splits=splits)


def test_shim_alltoall_warns_when_set_excludes_rank0(hvd):
    """Single-controller pass-through for a non-member controller is a
    documented contract, but it must be LOUD (ADVICE r3)."""
    import warnings as _w

    torch = pytest.importorskip("torch")
    from horovod_tpu import torch as hvdt

    ps = hvd.add_process_set([1, 2])
    try:
        with _w.catch_warnings(record=True) as got:
            _w.simplefilter("always")
            hvdt.alltoall(torch.arange(8, dtype=torch.float32))
            assert not any(
                "excludes rank 0" in str(w.message) for w in got
            ), "global alltoall must not warn"
        with _w.catch_warnings(record=True) as got:
            _w.simplefilter("always")
            out, recv = hvdt.alltoall(
                torch.arange(6, dtype=torch.float32).reshape(6, 1),
                splits=[3, 3],
                process_set=ps,
            )
            assert any(
                "excludes rank 0" in str(w.message) for w in got
            ), "non-member controller must warn"
            # pass-through contract: input unchanged, recv = full dim0
            np.testing.assert_array_equal(
                out.numpy(),
                np.arange(6, dtype=np.float32).reshape(6, 1),
            )
            assert recv.tolist() == [6]
    finally:
        hvd.remove_process_set(ps)


def test_adasum_pset_join_mask_composition(hvd, rng):
    """Join masking composes with an Adasum process set via buffer
    pre-zeroing (one compiled program per shape, mask-independent):
    joined MEMBERS contribute zero (Adasum identity) but take the
    result; joined NON-members keep their original input."""
    ps = hvd.add_process_set([0, 1, 2, 3])
    try:
        x = rank_major(lambda r: rng.normal(size=5))
        with hvd_mod.join_ranks([1, 6]):  # 1 = member, 6 = non-member
            out = hvd.allreduce(x, op=hvd_mod.Adasum, process_set=ps)
        # same op with rank 1's row zeroed, no join: must match exactly
        x_zeroed = x.copy()
        x_zeroed[1] = 0.0
        want = hvd.allreduce(x_zeroed, op=hvd_mod.Adasum, process_set=ps)
        np.testing.assert_allclose(
            np.asarray(out[0]), np.asarray(want[0]), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(out[1]), np.asarray(want[1]), rtol=1e-5, atol=1e-6
        )
        # joined non-member: original input, not zeros
        np.testing.assert_allclose(np.asarray(out[6]), x[6], rtol=1e-6)
    finally:
        hvd.remove_process_set(ps)
