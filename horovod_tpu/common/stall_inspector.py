"""Stall detection for eager collectives.

TPU-native rebuild of horovod/common/stall_inspector.cc/.h [V]
(SURVEY.md §2.1): the reference warns when some ranks have submitted a tensor
and others haven't for >60s. Under a single controller, cross-rank submission
skew cannot happen — the equivalent failure mode is a handle that is enqueued
but never synchronized/flushed (a leak or a deadlocked consumer), so that is
what we track: entries pending in the fusion queue past the warning age.
"""

from __future__ import annotations

import logging
import time
from typing import Dict

from .basics import HorovodInternalError

logger = logging.getLogger("horovod_tpu")


class StallInspector:
    def __init__(
        self, warning_seconds: float = 60.0, shutdown_seconds: float = 0.0
    ):
        self.warning_seconds = warning_seconds
        self.shutdown_seconds = shutdown_seconds
        self._pending: Dict[str, float] = {}
        self._warned: set = set()

    def record_enqueue(self, name: str) -> None:
        self._pending.setdefault(name, time.monotonic())

    def record_complete(self, name: str) -> None:
        self._pending.pop(name, None)
        self._warned.discard(name)

    def check(self) -> None:
        """Called once per fusion cycle (the reference checks once per
        background-loop cycle, stall_inspector.cc::CheckForStalledTensors
        [V])."""
        now = time.monotonic()
        for name, t in list(self._pending.items()):
            age = now - t
            if (
                self.shutdown_seconds > 0
                and age > self.shutdown_seconds
            ):
                raise HorovodInternalError(
                    f"collective '{name}' stalled for {age:.0f}s "
                    f"(> HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)"
                )
            if age > self.warning_seconds and name not in self._warned:
                self._warned.add(name)
                logger.warning(
                    "One or more collectives submitted but not completed "
                    "for %.0fs: %s. A consumer may be stalled.",
                    age,
                    name,
                )
