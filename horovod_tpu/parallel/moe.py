"""Expert parallelism: switch-style MoE FFN over the 'ep' axis.

The reference ships only the building block — the alltoall collective
(SURVEY.md §2.6: "the alltoall collective is the EP building block;
reference ships the primitive only"). Here it becomes the real thing:
experts are sharded across the 'ep' mesh axis, tokens are routed top-1
(switch transformer style) with a fixed capacity per expert (static
shapes — XLA requirement), dispatched to their expert's chip with
`lax.all_to_all`, transformed, and returned by the inverse all_to_all.

Per-device code for use inside shard_map.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class MoEParams(NamedTuple):
    router: jnp.ndarray  # [D, E_total]
    w1: jnp.ndarray  # [E_local, D, F]
    b1: jnp.ndarray  # [E_local, F]
    w2: jnp.ndarray  # [E_local, F, D]
    b2: jnp.ndarray  # [E_local, D]


def init_moe_params(key, d_model: int, d_ff: int, n_experts_local: int,
                    n_experts_total: int, dtype=jnp.float32) -> MoEParams:
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_ff)
    return MoEParams(
        router=(jax.random.normal(k1, (d_model, n_experts_total)) * s1).astype(dtype),
        w1=(jax.random.normal(k2, (n_experts_local, d_model, d_ff)) * s1).astype(dtype),
        b1=jnp.zeros((n_experts_local, d_ff), dtype),
        w2=(jax.random.normal(k3, (n_experts_local, d_ff, d_model)) * s2).astype(dtype),
        b2=jnp.zeros((n_experts_local, d_model), dtype),
    )


def moe_ffn(
    params: MoEParams,
    x,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
):
    """x: [T_local, D] tokens on this chip → [T_local, D].

    Routing: top-1 over E_total experts; expert e lives on chip
    e // E_local of the 'ep' axis. Tokens over capacity are dropped
    (switch-style; their output is zero and the residual connection
    carries them)."""
    ep = lax.axis_size(axis_name)
    t_local, d = x.shape
    e_local = params.w1.shape[0]
    e_total = e_local * ep

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        params.router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    # Per-destination-chip capacity (static).
    capacity = int(max(1, round(capacity_factor * t_local / ep)))

    dest_chip = expert_idx // e_local  # [T]
    # position of each token within its destination chip's buffer
    onehot_chip = jax.nn.one_hot(dest_chip, ep, dtype=jnp.int32)  # [T, ep]
    pos_in_chip = (jnp.cumsum(onehot_chip, axis=0) - 1)  # [T, ep]
    my_pos = jnp.take_along_axis(
        pos_in_chip, dest_chip[:, None], axis=1
    )[:, 0]  # [T]
    keep = my_pos < capacity

    # Scatter tokens into the dispatch buffer [ep, capacity, D]. Dropped
    # tokens get an out-of-range index → mode='drop' discards them, so
    # empty slots keep their init value (-1 sentinel in the expert map).
    idx_chip = jnp.where(keep, dest_chip, ep)
    idx_pos = jnp.where(keep, my_pos, 0)
    dispatch = (
        jnp.zeros((ep, capacity, d), x.dtype)
        .at[idx_chip, idx_pos]
        .set(x, mode="drop")
    )
    token_expert = (
        jnp.full((ep, capacity), -1, jnp.int32)
        .at[idx_chip, idx_pos]
        .set((expert_idx % e_local).astype(jnp.int32), mode="drop")
    )

    # To each chip its tokens: [ep, C, D] -> all_to_all over axis 0.
    recv = lax.all_to_all(dispatch, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)
    recv_expert = lax.all_to_all(token_expert, axis_name, split_axis=0,
                                 concat_axis=0, tiled=True)
    # recv: [ep*C, D] tokens for MY local experts (concat over sources).
    recv = recv.reshape(ep * capacity, d)
    which_expert = recv_expert.reshape(ep * capacity)

    # Apply each local expert to its tokens (dense einsum over one-hot —
    # MXU-friendly, no gather/scatter in the hot loop).
    sel = jax.nn.one_hot(which_expert, e_local, dtype=recv.dtype)  # [N, E_l]
    h = jnp.einsum("nd,edf,ne->nf", recv, params.w1, sel)
    h = h + jnp.einsum("ef,ne->nf", params.b1, sel)
    h = jax.nn.gelu(h)
    y = jnp.einsum("nf,efd,ne->nd", h, params.w2, sel)
    y = y + jnp.einsum("ed,ne->nd", params.b2, sel)
    # tokens that carried expert=-1 (padding) produce zeros
    y = y * (which_expert >= 0)[:, None]

    # Return to origin chips: inverse all_to_all.
    y_back = lax.all_to_all(
        y.reshape(ep, capacity, d), axis_name, split_axis=0, concat_axis=0,
        tiled=True,
    ).reshape(ep, capacity, d)

    # Un-scatter: token i's result sits at [dest_chip[i], my_pos[i]].
    out = y_back[idx_chip, idx_pos]
    out = jnp.where(keep[:, None], out, 0.0)
    return (out * gate[:, None]).astype(x.dtype)
