#!/usr/bin/env bash
# Round-4 part f: chunked fused linear-cross-entropy A/B on the LM
# benches (ops/fused_xent.py, BENCH_FUSED_XENT) — see the experiment
# comment above the cap list. Runs after the c->d->e chain drains;
# same skip-if-done + probe-gated discipline.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

# Wait until the whole c->d->e chain AND any in-flight bench claim
# are gone (one TPU process at a time — docs/perf.md operational
# rules; an earlier draft gated only on part e and could have
# stacked a claim on top of part c).
while pgrep -f "chipwork_r04[cde].sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce)?.py" >/dev/null 2>&1; do
  sleep 120
done

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}
wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}
run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}
cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

# Part f: chunked fused linear-cross-entropy A/B (ops/fused_xent.py,
# BENCH_FUSED_XENT) — the round-4 HBM-traffic experiment on the LM
# benches: same configs as the committed dense captures, plus the
# memory-headroom config (batch 32, no remat) the fused loss is meant
# to unlock.

cap gpt2_fxent         env BENCH_MODEL=gpt2_medium BENCH_FUSED_XENT=1 python bench_lm.py
cap gpt2_best_fxent    env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FLASH_BLOCK=256 BENCH_FUSED_XENT=1 python bench_lm.py
cap gpt2_b32_fxent     env BENCH_MODEL=gpt2_medium BENCH_BATCH=32 BENCH_REMAT=0 BENCH_FUSED_XENT=1 python bench_lm.py
cap bert_fxent         env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FUSED_XENT=1 python bench_lm.py

echo "=== chipwork_r04f complete $(date -u +%H:%M)" >&2
