"""Keras callbacks for the TF shim — real ``keras.callbacks.Callback``
subclasses (ref: horovod/tensorflow/keras/callbacks.py [V]): the four
the reference ships, adapted to the shim's collectives so
``model.fit(callbacks=[...])`` works unchanged for a ported script.

The framework-neutral twins in :mod:`horovod_tpu.callbacks` serve JAX
training loops; these serve Keras's callback protocol (on_train_begin /
on_epoch_end with a mutable ``logs`` dict, ``model.optimizer`` LR
mutation).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import tensorflow as tf

from . import allreduce, broadcast_variables
from ..ops.reduction_ops import Average


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast model + optimizer variables from root_rank on the
    first batch (ref: the same-named callback [V] — after a rank-0
    restore, every worker starts identical)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        # After the first step the optimizer has created its slots;
        # broadcasting then covers them too (the reference broadcasts
        # on_batch_end of batch 0 for exactly this reason [V]).
        if not self._done:
            broadcast_variables(self.model.variables, self.root_rank)
            if getattr(self.model, "optimizer", None) is not None:
                broadcast_variables(
                    self.model.optimizer.variables, self.root_rank
                )
            self._done = True


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics across workers before logging (ref:
    MetricAverageCallback [V])."""

    def __init__(self, process_set=None):
        super().__init__()
        self.process_set = process_set

    def on_epoch_end(self, epoch, logs: Optional[dict] = None):
        if not logs:
            return
        for key in list(logs.keys()):
            value = logs[key]
            if isinstance(value, (int, float, np.floating, np.integer)):
                avg = allreduce(
                    tf.constant(float(value), tf.float32),
                    op=Average,
                    name=f"metric.{key}",
                    process_set=self.process_set,
                )
                logs[key] = float(avg.numpy())


class LearningRateWarmupCallback(tf.keras.callbacks.Callback):
    """Ramp LR from lr/world to lr over warmup_epochs (ref:
    LearningRateWarmupCallback [V] — the gradual-warmup recipe of the
    large-batch papers the reference cites)."""

    def __init__(
        self,
        initial_lr: float,
        warmup_epochs: int = 5,
        momentum_correction: bool = True,
        steps_per_epoch: Optional[int] = None,
        verbose: bool = False,
    ):
        super().__init__()
        self.initial_lr = float(initial_lr)
        self.warmup_epochs = int(warmup_epochs)
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._epoch = 0.0
        self._base_momentum = None
        self._restored = False

    def _set_lr(self, lr: float) -> None:
        opt = self.model.optimizer
        # Keras 3 exposes .learning_rate as a Variable
        opt.learning_rate.assign(lr)
        if self.momentum_correction and hasattr(opt, "momentum"):
            # The reference rescales momentum with the LR during the
            # ramp so the effective update magnitude tracks the target
            # schedule (horovod keras callbacks, momentum_correction
            # [V]), restoring it when warmup ends.
            if self._base_momentum is None:
                try:
                    self._base_momentum = float(opt.momentum)
                except (TypeError, ValueError):
                    self._base_momentum = None
            if self._base_momentum:
                opt.momentum = self._base_momentum * (
                    lr / self.initial_lr
                )

    def _restore_momentum(self) -> None:
        opt = self.model.optimizer
        if (
            self.momentum_correction
            and self._base_momentum
            and hasattr(opt, "momentum")
        ):
            opt.momentum = self._base_momentum

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = float(epoch)
        if epoch >= self.warmup_epochs and not self._restored:
            # land exactly on initial_lr when the ramp completes
            self.model.optimizer.learning_rate.assign(self.initial_lr)
            self._restore_momentum()
            self._restored = True

    def on_batch_begin(self, batch, logs=None):
        if self._epoch >= self.warmup_epochs:
            return
        from ..common import basics

        size = basics.size() if basics.is_initialized() else 1
        if self.steps_per_epoch:
            # +1: the ramp hits exactly initial_lr on the LAST warmup
            # batch (the reference's epoch + (batch+1)/steps recipe [V])
            progress = self._epoch + (batch + 1) / self.steps_per_epoch
        else:
            progress = self._epoch
        frac = min(progress / max(self.warmup_epochs, 1e-9), 1.0)
        # lr(t) = initial_lr/size + frac · (initial_lr − initial_lr/size)
        lr = self.initial_lr / size * (1 + frac * (size - 1))
        self._set_lr(lr)
        if self.verbose and batch == 0:
            print(f"warmup epoch {self._epoch}: lr={lr:.6f}")


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Multiply the LR by ``multiplier(epoch)`` inside [start_epoch,
    end_epoch) (ref: LearningRateScheduleCallback [V])."""

    def __init__(
        self,
        initial_lr: float,
        multiplier,
        start_epoch: int = 0,
        end_epoch: Optional[int] = None,
    ):
        super().__init__()
        self.initial_lr = float(initial_lr)
        self.multiplier = (
            multiplier if callable(multiplier) else (lambda e: multiplier)
        )
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        if epoch < self.start_epoch:
            return
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        self.model.optimizer.learning_rate.assign(
            self.initial_lr * float(self.multiplier(epoch))
        )
