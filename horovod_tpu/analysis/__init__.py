"""Static analysis over lowered programs + runtime schedule audit.

Every structural guarantee this repo sells — N independent per-bucket
collectives (overlap), int8 on the inter hop only (two-level wire),
donated carries and ``decode_compiles==1`` (serving), guard overhead
exactly zero (integrity) — used to be enforced by ad-hoc
``lowered.as_text()`` regex asserts scattered across test files and
bench harnesses. This package promotes program-invariant checking to a
subsystem:

* :mod:`hlo_parse` — a structured parser over ``jit(...).lower()``
  StableHLO text producing a typed :class:`ProgramGraph`: collective
  ops with replica groups, operand dtypes/shapes/byte counts, def-use
  edges between collectives, and donation (``jax.buffer_donor``)
  coverage.
* :mod:`rules` — a declarative invariant engine over ProgramGraphs
  (and runtime counter dicts), each rule yielding structured findings
  with the offending HLO snippet.
* :mod:`sched_audit` — the runtime half: every eager fused dispatch
  folds into a per-rank rolling schedule fingerprint, published
  through the rendezvous KV on the ``HOROVOD_AUDIT_STEPS`` cadence so
  the elastic driver can flag a schedule-divergent rank (reason
  ``sched_divergence``) *before* the mismatch manifests as a
  collective hang.

``scripts/hlo_audit.py`` evaluates the rule catalog over the canonical
program roster; the five structure-asserting test files and the bench
harnesses' lowered-module gates share this parser instead of per-file
regex. docs/analysis.md is the catalog + runbook.
"""

from . import rules, sched_audit
from .hlo_parse import (
    COLLECTIVE_KINDS,
    ArgInfo,
    Collective,
    ProgramGraph,
    TensorType,
    parse_module,
)
from .rules import (
    CollectiveCount,
    CompileBudget,
    DonationCoverage,
    Finding,
    GuardOverhead,
    NoInterCollectiveDefUse,
    Report,
    ReplicaGroupStructure,
    WireDtype,
    expect,
    run_rules,
)

__all__ = [
    "COLLECTIVE_KINDS",
    "ArgInfo",
    "Collective",
    "ProgramGraph",
    "TensorType",
    "parse_module",
    "rules",
    "sched_audit",
    "CollectiveCount",
    "CompileBudget",
    "DonationCoverage",
    "Finding",
    "GuardOverhead",
    "NoInterCollectiveDefUse",
    "Report",
    "ReplicaGroupStructure",
    "WireDtype",
    "expect",
    "run_rules",
]
