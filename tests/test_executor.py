"""Executor tests (ref model: test/single/test_ray.py's
RayExecutor start/run/shutdown coverage [V], minus ray)."""

import os
import sys
import textwrap

import pytest

from horovod_tpu.executor import Executor, RayExecutor, run


def test_run_collects_per_rank_results():
    with Executor(num_workers=2) as ex:
        results = ex.run(os.getenv, args=("HOROVOD_RANK",))
    assert results == ["0", "1"]


def test_executor_env_contract():
    with Executor(num_workers=2, env={"MY_FLAG": "7"}) as ex:
        sizes = ex.run(os.getenv, args=("HOROVOD_SIZE",))
        flags = ex.run(os.getenv, args=("MY_FLAG",))
    assert sizes == ["2", "2"]
    assert flags == ["7", "7"]


def test_execute_alias_and_ray_name():
    # RayExecutor subclasses Executor; without ray installed it falls
    # back to the local runner transparently (use_ray auto-detects)
    assert issubclass(RayExecutor, Executor)
    ex = RayExecutor(num_workers=1)
    assert ex.use_ray is False  # sandbox has no ray
    ex.start()
    try:
        assert ex.execute(os.getenv, args=("HOROVOD_RANK",)) == ["0"]
    finally:
        ex.shutdown()


def test_per_host_placement_results_per_process():
    """per-host launches one process per host driving all local slots;
    results come back one per process, keyed by lead rank."""
    with Executor(num_workers=2, placement="per-host") as ex:
        results = ex.run(os.getenv, args=("HOROVOD_RANK",))
    # single local host → one process, rank 0, driving both slots
    assert results == ["0"]


def test_run_one_shot_helper():
    results = run(os.getenv, args=("HOROVOD_LOCAL_RANK",), num_proc=2)
    assert results == ["0", "0"]  # per-slot: each rank is its own host


def test_worker_exception_surfaces():
    """The rank's actual exception text must reach the driver, not just
    an exit code."""
    with Executor(num_workers=2) as ex:
        with pytest.raises(RuntimeError, match="raised: ValueError"):
            ex.run(int, args=("not-a-number",))


def test_run_before_start_raises():
    ex = Executor(num_workers=1)
    with pytest.raises(RuntimeError, match="before start"):
        ex.run(os.getenv, args=("HOME",))


@pytest.mark.slow
def test_distributed_function(tmp_path):
    """A function using jax.distributed + collectives across 2 executor
    ranks — the RayExecutor training-function pattern [V]."""
    mod = tmp_path / "hvd_exec_job.py"
    mod.write_text(
        textwrap.dedent(
            """
            def train():
                import jax
                jax.config.update("jax_platforms", "cpu")
                import numpy as np
                import horovod_tpu as hvd

                hvd.init()
                x = hvd.shard_from_rank_fn(
                    lambda r: np.full((2,), float(r + 1), np.float32),
                    hvd.mesh(),
                )
                out = hvd.allreduce(x, op=hvd.Sum)
                local = np.asarray(out.addressable_shards[0].data)
                return float(local.ravel()[0]), hvd.rank(), hvd.size()
            """
        )
    )
    sys.path.insert(0, str(tmp_path))
    try:
        import hvd_exec_job

        with Executor(
            num_workers=2, env={"PYTHONPATH": str(tmp_path)}
        ) as ex:
            results = ex.run(hvd_exec_job.train)
    finally:
        sys.path.remove(str(tmp_path))
    assert results == [(3.0, 0, 2), (3.0, 1, 2)]


# ------------------------------------------------- elastic ray surface


class _FakeRay:
    """ray-module shape for RayHostDiscovery: nodes() + is_initialized."""

    def __init__(self, nodes):
        self._nodes = nodes

    def is_initialized(self):
        return True

    def nodes(self):
        return self._nodes


def test_ray_host_discovery_maps_nodes(monkeypatch):
    from horovod_tpu import executor as ex_mod
    from horovod_tpu.executor import RayHostDiscovery

    fake = _FakeRay(
        [
            {"Alive": True, "NodeManagerAddress": "10.0.0.1",
             "Resources": {"CPU": 8.0}},
            {"Alive": False, "NodeManagerAddress": "10.0.0.2",
             "Resources": {"CPU": 8.0}},      # dead → excluded
            {"Alive": True, "NodeManagerAddress": "10.0.0.3",
             "Resources": {}},                # no CPUs → excluded
        ]
    )
    monkeypatch.setattr(ex_mod, "_ray_or_none", lambda: fake)
    hosts = RayHostDiscovery(cpus_per_slot=4).find_available_hosts_and_slots()
    assert [(h.hostname, h.slots) for h in hosts] == [("10.0.0.1", 2)]


def test_ray_host_discovery_slots_override(monkeypatch):
    from horovod_tpu import executor as ex_mod
    from horovod_tpu.executor import RayHostDiscovery

    fake = _FakeRay(
        [{"Alive": True, "NodeManagerAddress": "10.0.0.1",
          "Resources": {"CPU": 96.0}}]
    )
    monkeypatch.setattr(ex_mod, "_ray_or_none", lambda: fake)
    hosts = RayHostDiscovery(
        slots_per_host=1
    ).find_available_hosts_and_slots()
    assert [(h.hostname, h.slots) for h in hosts] == [("10.0.0.1", 1)]


def test_ray_host_discovery_without_ray_is_empty():
    from horovod_tpu.executor import RayHostDiscovery

    assert RayHostDiscovery().find_available_hosts_and_slots() == []


def test_elastic_ray_executor_requires_ray_or_discovery():
    from horovod_tpu.executor import ElasticRayExecutor

    with pytest.raises(RuntimeError, match="discovery"):
        ElasticRayExecutor().start()


def test_elastic_ray_executor_run_before_start():
    from horovod_tpu.executor import ElasticRayExecutor

    with pytest.raises(RuntimeError, match="before start"):
        ElasticRayExecutor(discovery=object()).run(os.getenv, ("HOME",))


@pytest.mark.slow
def test_elastic_ray_executor_end_to_end():
    """Scripted discovery (the documented no-ray mode) over localhost:
    the elastic driver launches the gang, the payload machinery returns
    per-rank results of the final gang."""
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.executor import ElasticRayExecutor
    from horovod_tpu.runner.hosts import HostInfo

    with ElasticRayExecutor(
        min_np=2,
        max_np=2,
        discovery=FixedHosts([HostInfo(hostname="127.0.0.1", slots=2)]),
        start_timeout=120.0,
    ) as ex:
        results = ex.run(os.getenv, args=("HOROVOD_RANK",))
    assert results == ["0", "1"]


@pytest.mark.slow
def test_elastic_ray_executor_surfaces_worker_exception():
    """When the gang fails and the blacklist drains capacity, the
    rank's actual exception (from the failed epoch's result pickle)
    must surface — not a generic exit code or 'no gang launched'."""
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.executor import ElasticRayExecutor
    from horovod_tpu.runner.hosts import HostInfo

    with ElasticRayExecutor(
        min_np=1,
        max_np=1,
        discovery=FixedHosts([HostInfo(hostname="127.0.0.1", slots=1)]),
        start_timeout=5.0,
    ) as ex:
        with pytest.raises(RuntimeError, match="raised: ValueError"):
            ex.run(int, args=("not-a-number",))


def test_executor_worker_epoch_subdir(tmp_path):
    """With HOROVOD_ELASTIC_EPOCH set the worker writes its result into
    the per-epoch subdirectory (stale-epoch isolation for elastic
    executors); without it, flat (plain Executor contract)."""
    import pickle
    import subprocess

    payload = tmp_path / "p.pkl"
    with open(payload, "wb") as f:
        pickle.dump((len, (("abc"),), {}), f)
    base_env = {
        **os.environ,
        "HOROVOD_EXECUTOR_OUT": str(tmp_path),
        "HOROVOD_RANK": "4",
    }
    subprocess.run(
        [sys.executable, "-m", "horovod_tpu._executor_worker",
         str(payload)],
        env={**base_env, "HOROVOD_ELASTIC_EPOCH": "2"},
        check=True,
    )
    with open(tmp_path / "epoch.2" / "result.4.pkl", "rb") as f:
        assert pickle.load(f) == ("ok", 3)
    subprocess.run(
        [sys.executable, "-m", "horovod_tpu._executor_worker",
         str(payload)],
        env=base_env,
        check=True,
    )
    with open(tmp_path / "result.4.pkl", "rb") as f:
        assert pickle.load(f) == ("ok", 3)


def test_collect_results_surfaces_error_over_missing(tmp_path):
    """Failed gang: rank 0 was SIGTERM'd (no pickle), rank 1 wrote its
    error — the error must win over 'rank 0 produced no result'."""
    import pickle

    from horovod_tpu.executor import _collect_results

    with open(tmp_path / "result.1.pkl", "wb") as f:
        pickle.dump(("error", "ValueError: boom"), f)
    with pytest.raises(RuntimeError, match="rank 1 raised: ValueError"):
        _collect_results(str(tmp_path), [0, 1], 1)


def test_collect_results_success_path_unchanged(tmp_path):
    import pickle

    from horovod_tpu.executor import _collect_results

    for r, v in ((0, "a"), (1, "b")):
        with open(tmp_path / f"result.{r}.pkl", "wb") as f:
            pickle.dump(("ok", v), f)
    assert _collect_results(str(tmp_path), [0, 1], 0) == ["a", "b"]


@pytest.mark.slow
def test_spark_run_elastic_parity():
    """horovod.spark.run_elastic one-shot shape [V]: fixed local gang,
    per-rank results of the final gang, no discovery source needed."""
    from horovod_tpu.spark import run_elastic

    results = run_elastic(
        os.getenv, args=("HOROVOD_RANK",), num_proc=2,
        start_timeout=120.0,
    )
    assert results == ["0", "1"]


def test_run_elastic_rejects_gang_below_min_np():
    """num_proc < min_np on a fixed local gang can never form: must be
    an immediate ValueError, not an opaque start_timeout 600s later."""
    from horovod_tpu.executor import run_elastic

    with pytest.raises(ValueError, match="min_np"):
        run_elastic(os.getenv, num_proc=1, min_np=2)


def test_run_elastic_sizes_default_gang_to_min_np():
    """num_proc omitted + min_np set: the fixed local gang is sized to
    min_np (the reference defaults num_proc to cluster parallelism, not
    1 — a 1-slot gang would deadlock against min_np=2)."""
    from horovod_tpu.executor import run_elastic

    results = run_elastic(
        os.getenv, args=("HOROVOD_RANK",), min_np=2, start_timeout=120.0
    )
    assert results == ["0", "1"]


@pytest.mark.slow
def test_run_ships_closures_and_real_collectives():
    """The payload must travel by VALUE (cloudpickle), not by module
    reference: a closure over local state, running a real hvd collective
    in every worker — the horovod.spark.run contract for script- and
    notebook-defined train functions [V]. (Plain pickle would reject
    the closure outright.)"""
    pytest.importorskip("cloudpickle")
    from horovod_tpu.executor import run

    scale = 10.0  # closed-over local -> unpicklable by reference

    def train():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        out = hvd.allreduce(
            hvd.replicate(np.float32([hvd.rank() + 1.0])), op=hvd.Sum
        )
        return float(hvd.my_row(out)[0]) * scale

    results = run(train, num_proc=2)
    assert results == [30.0, 30.0]
