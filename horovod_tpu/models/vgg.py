"""VGG family — the reference's hardest-scaling benchmark model
(ref: docs/benchmarks.rst — VGG-16 reaches only ~68% of linear at 128
GPUs because its 138M params make allreduce dominate [V]; BASELINE.md
reference table row 3). Useful here for exactly that reason: it
stress-tests the fusion buffer and gradient-collective path with a
param:FLOP ratio an order worse than ResNet's.

TPU-first choices: NHWC, bf16 compute with fp32 head, the classifier's
two 4096-wide Dense layers are plain MXU matmuls (the reference's
cuDNN-era grouping has no analog to translate).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

# Stage widths and conv counts for the 16-layer configuration "D"
# (the one the reference benchmarks [V]).
_VGG16_STAGES: Tuple[Tuple[int, int], ...] = (
    (64, 2), (128, 2), (256, 3), (512, 3), (512, 3)
)


class VGG(nn.Module):
    stages: Sequence[Tuple[int, int]] = _VGG16_STAGES
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    classifier_width: int = 4096
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for width, n_convs in self.stages:
            for _ in range(n_convs):
                x = nn.Conv(
                    width, (3, 3), padding="SAME", dtype=self.dtype
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), (2, 2))
        x = x.reshape(x.shape[0], -1)
        for _ in range(2):
            x = nn.Dense(self.classifier_width, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )


def VGG16(**kwargs) -> VGG:
    return VGG(stages=_VGG16_STAGES, **kwargs)
