"""Elastic state for the TF shim: ``TensorFlowKerasState``.

Parity target: ``horovod.tensorflow.elastic.TensorFlowKerasState`` [V]
(SURVEY.md §2.5 "Elastic worker API") — wrap a compiled Keras model
(+ scalars like epoch/batch) so elastic training can ``commit()``
(host snapshot of weights + optimizer variables), ``restore()`` (roll
back to the last commit), and ``sync()`` (broadcast from the new
rank 0 after a membership change). Use with ``hvd.elastic.run``
exactly like ``JaxState``/``TorchState``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np
import tensorflow as tf

from ..elastic.state import ObjectState, State  # noqa: F401 — re-export
from ..elastic.worker import run  # noqa: F401 — hvd.tensorflow.elastic.run


def _optimizer_variables(model) -> List:
    opt = getattr(model, "optimizer", None)
    if opt is None:
        return []
    # Keras 3: .variables is a property (list); Keras 2/TF: a method
    variables = getattr(opt, "variables", None)
    if callable(variables):
        variables = variables()
    return list(variables or [])


class TensorFlowKerasState(ObjectState):
    """Commit/restore/sync over a (compiled) Keras model (ref:
    horovod/tensorflow/elastic.py TensorFlowKerasState [V])."""

    def __init__(self, model, **kwargs: Any) -> None:
        self.model = model
        self._saved_weights: Optional[List[np.ndarray]] = None
        self._saved_opt: Optional[Dict[str, np.ndarray]] = None
        super().__init__(**kwargs)
        self.save()

    @staticmethod
    def _var_key(var, index: int) -> str:
        return getattr(var, "path", None) or getattr(
            var, "name", f"var_{index}"
        )

    def save(self) -> None:
        self._saved_weights = [
            np.asarray(w) for w in self.model.get_weights()
        ]
        # keyed by variable path: Keras optimizers grow variables on
        # first application (slot vars build lazily), so a positional
        # snapshot taken at compile time wouldn't align after training
        self._saved_opt = {
            self._var_key(v, i): np.asarray(v)
            for i, v in enumerate(_optimizer_variables(self.model))
        }
        super().save()

    def restore(self) -> None:
        if self._saved_weights is not None:
            # set_weights copies; no defensive copy needed
            self.model.set_weights(self._saved_weights)
        saved = self._saved_opt or {}
        for i, var in enumerate(_optimizer_variables(self.model)):
            key = self._var_key(var, i)
            if key in saved:
                var.assign(saved[key])
            else:
                # slot var born after the snapshot (e.g. momentum built
                # by the failed attempt's first step): its state at
                # snapshot time was "not yet existing" = zeros.
                # tf.zeros handles both Keras-3 string dtypes and
                # legacy tf.DType (np.zeros chokes on the latter)
                var.assign(tf.zeros(var.shape, dtype=var.dtype))
        super().restore()

    def sync(self) -> None:
        from . import broadcast_variables

        broadcast_variables(self.model.weights, root_rank=0)
        opt_vars = _optimizer_variables(self.model)
        if opt_vars:
            broadcast_variables(opt_vars, root_rank=0)
        super().sync()  # scalar attributes via broadcast_object
        self.save()
