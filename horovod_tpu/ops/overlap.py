"""Backward-interleaved gradient exchange: bucketed in-backprop
collectives.

The reference hides communication by firing per-tensor allreduces from
autograd hooks *during* backprop (ref: horovod/torch/optimizer.py
`_DistributedOptimizer` hook machinery [V], Sergeev & Del Balso,
arXiv 1802.05799 §3). Under XLA the equivalent lever is dataflow, not
hooks: the compiler overlaps a collective with remaining backward
compute exactly when the collective's operands do not depend on that
compute. A single exchange over the whole gradient tree (or one fused
buffer concatenating it) is data-dependent on the LAST gradient
produced, so there is structurally nothing to overlap — the exchange
becomes a terminal barrier after backprop.

This module re-creates the hook-style overlap inside one jitted step:

* :func:`build_bucket_schedule` partitions the gradient pytree into
  size-balanced, dtype-homogeneous buckets ordered by REVERSE flatten
  order — the DDP heuristic for backprop production order (the last
  layers' gradients materialize first, so their bucket's collective
  can launch while earlier layers are still differentiating).
* :func:`bucketed_allreduce` emits ONE independent collective per
  bucket (concat members → collective → split), so the compiled HLO
  contains N collectives whose operands are disjoint slices of the
  gradient tree — each launches at its own dataflow frontier, and the
  XLA scheduler runs bucket k's wire time against bucket k-1..0's
  remaining backward compute. Composes with everything the fused wire
  stack built: per-bucket wire format (``Compression.*`` including
  block-scaled int8 with per-bucket stochastic-rounding seeds),
  error-feedback residuals sliced per bucket, the prescale fold,
  process sets, and join masks.
* :func:`overlap_boundary` is the `jax.custom_vjp` marker: identity on
  the forward, bucketed exchange on the cotangents in the backward —
  so ``value_and_grad(..., overlap_buckets=N)`` returns gradients that
  were ALREADY reduced inside backprop, the reference's hook semantics
  with the compiler doing the scheduling (pattern ref: Xu et al.,
  arXiv 2004.13336 — per-shard decomposition is how XLA-era stacks
  recover the overlap).

Why bit-exactness holds for ``op=Sum`` fp32: `psum` over a
concatenation is elementwise identical to per-leaf `psum` (same
cross-replica addition order per element), so bucketing changes the
schedule, never the sum. Quantized wires change block geometry with
bucket geometry; parity there is within the two-stage quantum bound
(tests/test_overlap.py asserts both).

Schedules are cached per (treedef, leaf shapes/dtypes, knobs) with
hit/miss counters — the compile-churn tripwire: a training loop that
rebuilds its schedule (or retraces its step) every iteration shows up
as cache misses, not silence.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common.topology import WORLD_AXIS
from ..common.process_sets import ProcessSet
from ..ops.reduction_ops import Average, Sum, resolve_op
from . import traced
from .compression import Compression, Compressor


class BucketSchedule(NamedTuple):
    """A static partition of the gradient tree's leaves into buckets.

    ``buckets`` holds leaf indices (into the flattened tree) per
    bucket, in EMISSION order — bucket 0's members are produced first
    in backprop (reverse flatten order), so its collective launches
    first. ``passthrough`` are leaves excluded from the exchange
    (float0 cotangents of non-differentiable leaves)."""

    buckets: Tuple[Tuple[int, ...], ...]
    bucket_bytes: Tuple[int, ...]
    total_bytes: int
    passthrough: Tuple[int, ...] = ()

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def _leaf_key(leaf) -> Tuple:
    return (tuple(np.shape(leaf)), str(jnp.result_type(leaf)))


def _is_float0(leaf) -> bool:
    return jnp.result_type(leaf) == jax.dtypes.float0


# -- schedule cache ----------------------------------------------------
# One schedule per (structure, geometry, knobs): rebuilt schedules are
# the symptom of retrace churn, so the cache is instrumented. Bounded
# LRU-ish (dict insertion order) so a pathological caller can't grow it.

_CACHE: dict = {}
_CACHE_CAP = 256
_STATS = {"hits": 0, "misses": 0, "disk_hits": 0}

# Persisted beside the executables (HOROVOD_EXE_CACHE sidecar,
# common/exe_cache.py): the partition DECISION that produced each
# persisted bucketed executable. A restarted worker re-derives the
# same buckets from the same inputs today; the sidecar makes the
# decision durable against heuristic drift — a recorded partition is
# replayed verbatim, so its exe-cache entries keep hitting even if
# build_bucket_schedule's balancing rule changes underneath it.
_SIDECAR = "overlap_schedule"


def schedule_cache_stats() -> dict:
    return dict(_STATS, size=len(_CACHE))


def reset_schedule_cache() -> None:
    _CACHE.clear()
    _STATS["hits"] = 0
    _STATS["misses"] = 0
    _STATS["disk_hits"] = 0


def _sidecar_key(key: tuple) -> str:
    import hashlib

    return hashlib.sha256(repr(key).encode()).hexdigest()[:24]


def _schedule_from_record(rec) -> Optional[BucketSchedule]:
    """A sidecar record → BucketSchedule, or None when malformed (a
    corrupt sidecar entry must read as a plain rebuild)."""
    try:
        return BucketSchedule(
            buckets=tuple(tuple(int(i) for i in b) for b in rec["buckets"]),
            bucket_bytes=tuple(int(b) for b in rec["bucket_bytes"]),
            total_bytes=int(rec["total_bytes"]),
            passthrough=tuple(int(i) for i in rec.get("passthrough", ())),
        )
    except (KeyError, TypeError, ValueError):
        return None


def _schedule_record(key: tuple, sched: BucketSchedule) -> dict:
    return {
        "buckets": [list(b) for b in sched.buckets],
        "bucket_bytes": list(sched.bucket_bytes),
        "total_bytes": int(sched.total_bytes),
        "passthrough": list(sched.passthrough),
        "n_leaves": sum(len(b) for b in sched.buckets)
        + len(sched.passthrough),
        "n_buckets": int(key[2]),
        "min_bucket_bytes": int(key[3]),
    }


def build_bucket_schedule(
    leaves: Sequence[Any],
    n_buckets: int,
    min_bucket_bytes: int = 0,
) -> BucketSchedule:
    """Partition ``leaves`` into at most ``n_buckets`` size-balanced
    buckets in reverse flatten order (DDP-style backprop production
    order). Buckets are dtype-homogeneous — a concat buffer carries one
    dtype, so a dtype flip forces a bucket boundary (like DDP's
    per-dtype buckets). Buckets smaller than ``min_bucket_bytes`` are
    merged forward where the dtype allows: below the floor the
    per-collective launch overhead outweighs any overlap win (the
    ``HOROVOD_OVERLAP_MIN_BYTES`` knob)."""
    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    passthrough = tuple(
        i for i, l in enumerate(leaves) if _is_float0(l)
    )
    order = [
        i for i in reversed(range(len(leaves))) if i not in passthrough
    ]
    if not order:
        return BucketSchedule((), (), 0, passthrough)
    nbytes = {
        i: int(np.prod(np.shape(leaves[i]), dtype=np.int64))
        * jnp.result_type(leaves[i]).itemsize
        for i in order
    }
    total = sum(nbytes.values())
    # balanced linear partition: close bucket k before adding a leaf
    # whose MIDPOINT crosses the k-th ideal boundary (k+1)·total/N —
    # the closest-boundary rule, so a large leaf lands on whichever
    # side of the boundary most of it lies
    target = total / n_buckets
    buckets, cur = [], []
    cum, cur_bytes, closed = 0, 0, 0
    cur_dtype = None
    for i in order:
        d = jnp.result_type(leaves[i])
        if cur and (
            cur_dtype != d
            or (
                closed < n_buckets - 1
                and cum + nbytes[i] / 2 >= (closed + 1) * target
            )
        ):
            buckets.append((tuple(cur), cur_bytes))
            closed += 1
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes[i]
        cum += nbytes[i]
        cur_dtype = d
    if cur:
        buckets.append((tuple(cur), cur_bytes))
    if min_bucket_bytes > 0:
        # forward pass: a bucket still under the floor absorbs the
        # next same-dtype bucket (once it clears the floor it stops —
        # no cascade past the target)
        merged = []
        for idxs, b in buckets:
            if (
                merged
                and merged[-1][1] < min_bucket_bytes
                and jnp.result_type(leaves[merged[-1][0][0]])
                == jnp.result_type(leaves[idxs[0]])
            ):
                pi, pb = merged[-1]
                merged[-1] = (pi + idxs, pb + b)
            else:
                merged.append((idxs, b))
        # an under-floor TAIL bucket merges backward
        if (
            len(merged) > 1
            and merged[-1][1] < min_bucket_bytes
            and jnp.result_type(leaves[merged[-2][0][0]])
            == jnp.result_type(leaves[merged[-1][0][0]])
        ):
            pi, pb = merged[-2]
            ti, tb = merged[-1]
            merged[-2:] = [(pi + ti, pb + tb)]
        buckets = merged
    return BucketSchedule(
        buckets=tuple(i for i, _ in buckets),
        bucket_bytes=tuple(b for _, b in buckets),
        total_bytes=total,
        passthrough=passthrough,
    )


def schedule_for(
    leaves: Sequence[Any],
    treedef,
    n_buckets: int,
    min_bucket_bytes: int = 0,
) -> BucketSchedule:
    """Cached :func:`build_bucket_schedule` keyed on tree structure +
    leaf geometry + knobs."""
    key = (
        str(treedef),
        tuple(_leaf_key(l) for l in leaves),
        int(n_buckets),
        int(min_bucket_bytes),
    )
    sched = _CACHE.get(key)
    if sched is not None:
        _STATS["hits"] += 1
        return sched
    _STATS["misses"] += 1
    from ..common import exe_cache as _exe_cache

    disk = _exe_cache.cache_dir()
    if disk:
        rec = _exe_cache.load_json(_SIDECAR).get(_sidecar_key(key))
        if rec is not None:
            sched = _schedule_from_record(rec)
            if sched is not None:
                _STATS["disk_hits"] += 1
                if len(_CACHE) >= _CACHE_CAP:
                    _CACHE.pop(next(iter(_CACHE)))
                _CACHE[key] = sched
                return sched
    sched = build_bucket_schedule(leaves, n_buckets, min_bucket_bytes)
    if disk:
        _exe_cache.persist_json(
            _SIDECAR, {_sidecar_key(key): _schedule_record(key, sched)}
        )
    if len(_CACHE) >= _CACHE_CAP:
        _CACHE.pop(next(iter(_CACHE)))
    _CACHE[key] = sched
    return sched


def default_buckets() -> int:
    """The config-driven default bucket count: ``HOROVOD_OVERLAP_BUCKETS``
    when ``HOROVOD_OVERLAP`` is enabled, else 0 (monolithic path).
    Reads the initialized runtime's config snapshot when there is one."""
    from ..common import basics

    cfg = basics.live_config()
    return cfg.overlap_buckets if cfg.overlap else 0


def default_min_bytes() -> int:
    from ..common import basics

    return basics.live_config().overlap_min_bytes


def _auto_stages(hier_stages, world: int):
    """Resolve a bucketed function's ``hier_stages`` argument:
    ``"auto"`` (the default) consults the HOROVOD_HIERARCHICAL
    topology decision for this axis size — when a real inter axis is
    present, every bucket's collective decomposes into intra RS ->
    inter hop on the 1/L shard -> intra AG (ops/traced.py recipe
    family); an explicit ``(intra_groups, inter_groups)`` tuple is
    used as-is (the test/bench injection point); ``None`` keeps the
    flat wire."""
    if hier_stages == "auto":
        from ..common import topology as _topo

        return _topo.hierarchy_stages(world=world)
    return hier_stages


def _publish(schedule: BucketSchedule) -> None:
    from ..common import metrics

    metrics.publish_overlap(
        schedule.n_buckets, schedule.bucket_bytes, schedule.total_bytes
    )


def bucketed_allreduce(
    grads,
    op=None,
    average: Optional[bool] = None,
    n_buckets: Optional[int] = None,
    compression: Compressor = Compression.none,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
    seed=0,
    residuals=None,
    mask=None,
    min_bucket_bytes: Optional[int] = None,
    schedule: Optional[BucketSchedule] = None,
    return_finite: bool = False,
    hier_stages="auto",
    groups=None,
):
    """Allreduce a gradient pytree as N independent per-bucket
    collectives (module docstring).

    ``groups`` restricts every bucket's collective to
    ``axis_index_groups`` of the flat axis (the local-SGD local phase:
    each slice reduces among its own ranks, zero inter-slice bytes).
    Mutually exclusive with the two-level routing (``hier_stages`` is
    ignored — there IS no inter hop), with process sets and with join
    masks; ``Average`` divides by the group size. Quantized wires ride
    the grouped two-stage recipe with the same EF residual contract.

    ``hier_stages`` routes each bucket through the TWO-LEVEL recipe
    (``traced.hierarchical_allreduce_groups``: intra RS -> inter
    collective on the 1/L shard -> intra AG) — ``"auto"`` (default)
    engages it exactly when ``HOROVOD_HIERARCHICAL`` resolves an inter
    axis for this topology; pass an explicit ``(intra, inter)`` group
    tuple or ``None`` to force/disable. Process sets and join masks
    degenerate to the flat wire (masked hierarchy has no uniform
    group shape). Quantized compressors place int8 on the INTER hop
    only (``Compression.hier_int8`` additionally rides bf16 intra —
    its documented eager placement, now honored on this path too);
    error-feedback residuals follow the hierarchical input-unit carry
    contract.

    Each bucket: concat its members' flattened leaves → ONE collective
    → split back. For the fp32/bf16 wires the collective is
    :func:`traced.allreduce` (process sets, join ``mask``, pre/post
    scale all compose); for a quantized-wire compression
    (``Compression.int8`` / ``int8_block`` / descendants) it is
    :func:`traced.quantized_allreduce` over the bucket buffer — block
    scales at the compressor's granularity, the prescale fold, and a
    per-bucket-decorrelated stochastic-rounding seed, exactly the PR-2
    monolithic recipe applied per bucket.

    ``residuals`` (error-feedback carry, quantized wires only): each
    bucket's carry joins its wire signal and the new per-bucket
    residual is sliced back to the member leaves; returns
    ``(reduced, new_residuals)``.

    ``mask`` is a [world] bool participation vector (the traced join
    mask): masked-out ranks contribute the identity and ``Average``
    divides by the live count. Sum/Average only — bucketing relies on
    reduction elementwise-ness over the concat (Adasum's whole-tensor
    dot products do not commute with concatenation; use the monolithic
    path for it).

    ``return_finite=True`` appends a scalar bool to the result: the
    non-finite sentinel (common/guard.py), ONE ``all(isfinite)``
    reduction per bucket buffer computed on the already-reduced values
    (replicated, so the flag agrees across ranks with no extra
    collective) AND'd across buckets. The guarded optimizers cond
    their update on it.
    """
    op = resolve_op(op, average)
    if op not in (Sum, Average):
        raise ValueError(
            "bucketed_allreduce supports op=Sum/Average only (Adasum "
            "and min/max/product do not commute with bucket concat); "
            "use the monolithic path for other ops"
        )
    if n_buckets is None:
        n_buckets = default_buckets() or 1
    if min_bucket_bytes is None:
        # same config deferral as n_buckets: the public surface and the
        # optimizer wrappers must build the SAME schedule for the same
        # tree (HOROVOD_OVERLAP_MIN_BYTES; pass 0 to disable merging)
        min_bucket_bytes = default_min_bytes()
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if schedule is None:
        schedule = schedule_for(
            leaves, treedef, n_buckets, min_bucket_bytes
        )
    _publish(schedule)

    quantized = getattr(compression, "quantized_wire", False)
    if groups is not None and (
        mask is not None
        or (process_set is not None and process_set.process_set_id != 0)
    ):
        raise NotImplementedError(
            "bucketed_allreduce(groups=) composes with neither "
            "process sets nor join masks"
        )
    if quantized:
        if process_set is not None and process_set.process_set_id != 0:
            raise NotImplementedError(
                "quantized-wire bucketed exchange over a process set is "
                "not supported (same restriction as the monolithic "
                "path); use fp32/bf16 compression or the global set"
            )
        if mask is not None:
            raise NotImplementedError(
                "join mask over the quantized bucketed wire is not "
                "supported; use fp32/bf16 compression under join"
            )
    elif residuals is not None:
        raise ValueError(
            "error_feedback requires a quantized-wire compression "
            "(Compression.int8); lossless/fp16 wires have no residual"
        )

    r_leaves = (
        treedef.flatten_up_to(residuals) if residuals is not None else None
    )
    out_leaves: list = [None] * len(leaves)
    res_leaves: list = [None] * len(leaves)
    for i in schedule.passthrough:
        out_leaves[i] = leaves[i]
        if r_leaves is not None:
            res_leaves[i] = r_leaves[i]

    stages = (
        None
        if groups is not None
        else _auto_stages(hier_stages, jax.lax.axis_size(axis_name))
    )
    if (
        stages is None
        and groups is None
        and hier_stages == "auto"
        and getattr(compression, "wire_format", None) == "int8_hier"
    ):
        # Compression.hier_int8 is an EXPLICIT per-call request: any
        # resolvable split qualifies, not just auto-mode evidence
        from ..common import topology as _topo

        stages = _topo.hierarchy_stages(
            world=jax.lax.axis_size(axis_name), mode="on"
        )
    if stages is not None and (
        (process_set is not None and process_set.process_set_id != 0)
        or mask is not None
    ):
        stages = None  # masked hierarchy degenerates to flat
    # Compression.hier_int8's eager contract, honored here: bf16 on
    # the intra hops under the int8 inter; plain int8 keeps the intra
    # hops exact (quantize only where bytes are scarce)
    hier_intra = (
        "bf16"
        if getattr(compression, "wire_format", None) == "int8_hier"
        else "fp32"
    )
    block = getattr(compression, "block_size", None)
    finite = None
    for b, idxs in enumerate(schedule.buckets):
        members = [leaves[i] for i in idxs]
        sizes = [int(np.prod(np.shape(m), dtype=np.int64)) for m in members]
        flat = (
            members[0].reshape(-1)
            if len(members) == 1
            else jnp.concatenate([m.reshape(-1) for m in members])
        )
        if quantized:
            # decorrelate rounding across buckets AND steps: stride the
            # caller's step seed by the bucket count (unique per
            # (step, bucket), monotone in the step like the monolithic
            # path's per-step seed)
            bseed = seed * schedule.n_buckets + b
            if r_leaves is not None:
                parts = [
                    r_leaves[i].reshape(-1).astype(flat.dtype)
                    for i in idxs
                ]
                r_flat = (
                    parts[0] if len(parts) == 1 else jnp.concatenate(parts)
                )
                if stages is not None:
                    out_flat, new_r = traced.hierarchical_allreduce_groups(
                        flat + r_flat, op=op, axis_name=axis_name,
                        stages=stages, intra_wire=hier_intra,
                        inter_wire="int8", seed=bseed, block_size=block,
                        prescale_factor=prescale_factor,
                        return_residual=True,
                    )
                else:
                    out_flat, new_r = traced.quantized_allreduce(
                        flat + r_flat, op=op, axis_name=axis_name,
                        seed=bseed, return_residual=True,
                        prescale_factor=prescale_factor, block_size=block,
                        groups=groups,
                    )
            elif stages is not None:
                # the two-level placement: int8 on the DCN hop only
                out_flat = traced.hierarchical_allreduce_groups(
                    flat, op=op, axis_name=axis_name, stages=stages,
                    intra_wire=hier_intra, inter_wire="int8",
                    seed=bseed, block_size=block,
                    prescale_factor=prescale_factor,
                )
                new_r = None
            else:
                out_flat = traced.quantized_allreduce(
                    flat, op=op, axis_name=axis_name, seed=bseed,
                    prescale_factor=prescale_factor, block_size=block,
                    groups=groups,
                )
                new_r = None
            if postscale_factor != 1.0:
                out_flat = out_flat * jnp.asarray(
                    postscale_factor, out_flat.dtype
                )
        elif stages is not None:
            wire, ctx = compression.compress(flat)
            red = traced.hierarchical_allreduce_groups(
                wire,
                op=op,
                axis_name=axis_name,
                stages=stages,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
            )
            out_flat = compression.decompress(red, ctx)
            new_r = None
        else:
            wire, ctx = compression.compress(flat)
            red = traced.allreduce(
                wire,
                op=op,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                process_set=process_set,
                axis_name=axis_name,
                mask=mask,
                groups=groups,
            )
            out_flat = compression.decompress(red, ctx)
            new_r = None
        if return_finite:
            # one scalar reduction over THIS bucket's reduced buffer —
            # the whole guard cost; AND'd into the step flag
            ok = traced.finite_scalar(out_flat)
            finite = ok if finite is None else jnp.logical_and(finite, ok)
        off = 0
        for i, sz in zip(idxs, sizes):
            out_leaves[i] = out_flat[off : off + sz].reshape(
                np.shape(leaves[i])
            )
            if r_leaves is not None:
                # carry keeps its init dtype (see optimizer.one_q)
                res_leaves[i] = (
                    new_r[off : off + sz]
                    .reshape(np.shape(leaves[i]))
                    .astype(r_leaves[i].dtype)
                )
            off += sz
    reduced = jax.tree_util.tree_unflatten(treedef, out_leaves)
    if return_finite and finite is None:  # schedule had no buckets
        finite = jnp.asarray(True)
    if residuals is None:
        return (reduced, finite) if return_finite else reduced
    new_res = jax.tree_util.tree_unflatten(treedef, res_leaves)
    if return_finite:
        return reduced, new_res, finite
    return reduced, new_res


# ----------------------------------------- sharded (ZeRO) bucket wire
#
# The ZeRO-2/3 exchange legs: per-bucket reduce-scatter of a gradient
# pytree INTO per-leaf shard slices, and the dual per-bucket all-gather
# of shard slices back to full leaves. Same schedule machinery and
# pane geometry as bucketed_allreduce (member leaves' padded [n, cols]
# panes concatenated column-wise, ONE collective per bucket), so the
# compiled step carries N independent collectives at their dataflow
# frontiers; the shard slice of each bucket's reduce-scatter output IS
# the per-rank storage slice — no full reduced-gradient buffer exists
# at any point. Wire formats ride per bucket (fp32 / bf16 cast /
# block-scaled int8 with pad exclusion by construction), resolved
# statically at trace time via resolve_wire / the WireTuner.

_WIRE_TUNER = None


def wire_tuner():
    """Process-wide WireTuner consulted by ``wire='auto'`` buckets.
    Trace-time choices freeze into the compiled step, so the tuner's
    explore-then-exploit plays out across RECOMPILES (the step harness
    / bench loop feeds ``record``, exactly like the OverlapTuner)."""
    global _WIRE_TUNER
    if _WIRE_TUNER is None:
        from ..common import basics
        from ..common.autotune import WireTuner

        _WIRE_TUNER = WireTuner(
            min_int8_bytes=basics.live_config().fusion_wire_min_bytes
        )
    return _WIRE_TUNER


def reset_wire_tuner() -> None:
    global _WIRE_TUNER
    _WIRE_TUNER = None


def resolve_wire(
    wire, bucket_bytes: int, itemsize: int = 4, key=None, hop=None
) -> str:
    """Static per-bucket wire-format resolution. Explicit formats pass
    through; ``'auto'`` resolves per bucket at TRACE time: under the
    ``HOROVOD_FUSION_WIRE_MIN_BYTES`` floor the quant tax always wins
    (fp32); above it the PR-2 premise prior picks int8 for 4-byte
    payloads — unless the WireTuner holds measured goodput for this
    bucket key, in which case the bandit's argmax wins (the step
    harness records observations across recompiles, the OverlapTuner
    pattern). Returns one of ``'fp32' | 'bf16' | 'int8'``.

    ``hop`` ∈ {None, 'intra', 'inter'} splits the tuner keyspace per
    hop of the two-level wire — (bucket-tier, hop) — so goodput can
    pick bf16-intra and int8-inter independently; the intra hop's
    candidate menu never includes int8 (ICI is fast: the quant tax
    can't pay for itself inside the slice), and ``bucket_bytes`` for
    the inter hop should be the 1/L shard the DCN actually carries."""
    if wire in (None, "fp32"):
        return "fp32"
    if wire in ("bf16", "int8"):
        if hop == "intra" and wire == "int8":
            return "fp32"  # int8 never rides the intra hop
        return wire
    if wire == "auto":
        tuner = wire_tuner()
        if int(bucket_bytes) < tuner.min_int8_bytes:
            return "fp32"
        candidates = (
            ("fp32", "bf16") if hop == "intra" else tuner.CANDIDATES
        )
        key = key if key is not None else ("bucket", int(bucket_bytes))
        if hop is not None:
            key = tuple(key) + (hop,)
        if any(
            tuner.goodput(key, c) > 0 for c in candidates
        ):
            return tuner.choose(
                key, int(bucket_bytes), itemsize=itemsize,
                candidates=candidates,
            )
        if "int8" in candidates and itemsize >= 4:
            return "int8"
        return "fp32"
    raise ValueError(f"unknown wire format {wire!r}")


def _leaf_panes(leaf, n):
    """One leaf's rank-major pane: flatten, zero-pad, [n, cols]."""
    from ..parallel.fsdp import pad_to

    return pad_to(leaf.reshape(-1), n).reshape(n, -1)


def bucketed_reduce_scatter(
    grads,
    op=None,
    average: Optional[bool] = None,
    n_buckets: Optional[int] = None,
    axis_name: str = WORLD_AXIS,
    wire: str = "fp32",
    wire_block: Optional[int] = None,
    seed=0,
    residuals=None,
    min_bucket_bytes: Optional[int] = None,
    schedule: Optional[BucketSchedule] = None,
    hier_stages="auto",
    groups=None,
):
    """Reduce-scatter a pytree as N independent per-bucket collectives,
    returning per-leaf SHARD slices (nonscalar leaf → its ``[cols]``
    rank shard, ``cols = ceil(size/world)``; 0-d leaf → replicated
    psum) — the ZeRO-2 gradient leg.

    ``groups`` (local-SGD local phase) restricts every collective to
    ``axis_index_groups`` of the flat axis: panes are ``[L, cols]``
    (L = group size), each group scatters among its own members —
    rank r receives the shard of its POSITION within its group — and
    ``Average`` divides by L. ``hier_stages`` is ignored (no inter
    hop exists inside a slice). Elementwise identical to a
    per-leaf ``psum_scatter`` for the fp32 wire (same per-element
    cross-replica sums), so shard values are bit-exact vs the
    monolithic ZeRO-1 path.

    ``wire`` picks the per-bucket format (``resolve_wire``): bf16
    casts the pane buffer, int8 rides
    :func:`~horovod_tpu.ops.traced.quantized_reducescatter` with
    ``wire_block``-scaled stochastic rounding. ``residuals`` (tree
    mirroring ``grads``, input units) is the error-feedback carry for
    lossy buckets: it joins the pane signal before the wire and the new
    per-leaf residual comes back in leaf geometry (exact-wire buckets
    return zero residuals — everything was transmitted). Returns
    ``(shards, new_residuals)`` when ``residuals`` is given.

    ``hier_stages`` (``"auto"`` = the HOROVOD_HIERARCHICAL topology
    decision) routes each bucket through
    :func:`traced.hierarchical_reducescatter` — intra RS of the pane
    buffer, inter hop on the 1/L panes (int8 there when the resolved
    wire is int8), so the ZeRO-2 gradient leg's DCN bytes drop L-fold.
    Error-feedback buckets keep the FLAT wire (the EF carry is defined
    against the flat pane quantization; see docs/design.md)."""
    op = resolve_op(op, average)
    if op not in (Sum, Average):
        raise ValueError(
            "bucketed_reduce_scatter supports op=Sum/Average only"
        )
    if n_buckets is None:
        n_buckets = default_buckets() or 1
    if min_bucket_bytes is None:
        min_bucket_bytes = default_min_bytes()
    if groups is not None:
        n = len(groups[0])
        groups = [list(g) for g in groups]
        stages = None
    else:
        n = jax.lax.axis_size(axis_name)
        stages = _auto_stages(hier_stages, n)
    if residuals is not None:
        stages = None  # EF carries are defined against the flat wire
    hier_L = None if stages is None else len(stages[0][0])
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    nonscalar = [
        i for i, g in enumerate(leaves)
        if np.ndim(g) > 0 and not _is_float0(g)
    ]
    if schedule is None:
        schedule = schedule_for(
            [leaves[i] for i in nonscalar], treedef,
            n_buckets, min_bucket_bytes,
        )
    _publish(schedule)
    r_leaves = (
        treedef.flatten_up_to(residuals) if residuals is not None else None
    )
    out: list = [None] * len(leaves)
    res_out: list = [None] * len(leaves)
    in_schedule = set(nonscalar)
    for i, g in enumerate(leaves):
        if i in in_schedule:
            continue
        if _is_float0(g) or not jnp.issubdtype(
            jnp.result_type(g), jnp.inexact
        ):
            out[i] = g  # passthrough (float0 cotangents etc.)
        else:
            red = jax.lax.psum(g, axis_name, axis_index_groups=groups)
            out[i] = red / n if op == Average else red
        if r_leaves is not None:
            res_out[i] = r_leaves[i]
    for b, idxs in enumerate(schedule.buckets):
        members = [leaves[nonscalar[j]] for j in idxs]
        panes = [_leaf_panes(m, n) for m in members]
        cols = [p.shape[1] for p in panes]
        buf = panes[0] if len(panes) == 1 else jnp.concatenate(
            panes, axis=1
        )
        if r_leaves is not None:
            rparts = [
                _leaf_panes(
                    r_leaves[nonscalar[j]].astype(buf.dtype), n
                )
                for j in idxs
            ]
            buf = buf + (
                rparts[0] if len(rparts) == 1
                else jnp.concatenate(rparts, axis=1)
            )
        if stages is not None:
            # two-level leg: the inter hop sees 1/L of the bucket, so
            # the wire decision is keyed (and sized) per hop
            bw = resolve_wire(
                wire, int(schedule.bucket_bytes[b]) // hier_L,
                itemsize=jnp.result_type(members[0]).itemsize,
                key=("zero_rs", b, buf.shape[1]), hop="inter",
            )
            bseed = seed * schedule.n_buckets + b
            red = traced.hierarchical_reducescatter(
                buf, op=op, axis_name=axis_name, stages=stages,
                intra_wire="bf16" if bw == "bf16" else "fp32",
                inter_wire=bw, seed=bseed, block_size=wire_block,
            )
            off = 0
            for j, c in zip(idxs, cols):
                i = nonscalar[j]
                out[i] = red[off : off + c].astype(
                    jnp.result_type(leaves[i])
                )
                off += c
            continue
        bw = resolve_wire(
            wire, int(schedule.bucket_bytes[b]),
            itemsize=jnp.result_type(members[0]).itemsize,
            key=("zero_rs", b, buf.shape[1]),
        )
        new_r = None
        if bw == "int8":
            bseed = seed * schedule.n_buckets + b
            if r_leaves is not None:
                red, new_r = traced.quantized_reducescatter(
                    buf, op=Sum, axis_name=axis_name, seed=bseed,
                    block_size=wire_block, return_residual=True,
                    groups=groups,
                )
            else:
                red = traced.quantized_reducescatter(
                    buf, op=Sum, axis_name=axis_name, seed=bseed,
                    block_size=wire_block, groups=groups,
                )
            if op == Average:
                red = red / jnp.asarray(n, red.dtype)
        else:
            wire_buf = buf.astype(jnp.bfloat16) if bw == "bf16" else buf
            red = jax.lax.psum_scatter(
                wire_buf, axis_name, scatter_dimension=0, tiled=False,
                axis_index_groups=groups,
            ).astype(buf.dtype)
            if op == Average:
                red = red / jnp.asarray(n, red.dtype)
            if r_leaves is not None:
                # exact wire transmits everything: residual drains;
                # bf16 carries the local cast error (input units)
                new_r = (
                    buf - wire_buf.astype(buf.dtype)
                    if bw == "bf16"
                    else jnp.zeros_like(buf)
                )
        off = 0
        for j, c in zip(idxs, cols):
            i = nonscalar[j]
            out[i] = red[off : off + c].astype(
                jnp.result_type(leaves[i])
            )
            if r_leaves is not None:
                size = int(np.prod(np.shape(leaves[i]), dtype=np.int64))
                res_out[i] = (
                    new_r[:, off : off + c]
                    .reshape(-1)[:size]
                    .reshape(np.shape(leaves[i]))
                    .astype(r_leaves[i].dtype)
                )
            off += c
    shards = jax.tree_util.tree_unflatten(treedef, out)
    if residuals is None:
        return shards
    return shards, jax.tree_util.tree_unflatten(treedef, res_out)


def bucketed_shard_all_gather(
    shards,
    like,
    n_buckets: Optional[int] = None,
    axis_name: str = WORLD_AXIS,
    wire: str = "fp32",
    wire_block: Optional[int] = None,
    seed=0,
    residuals=None,
    min_bucket_bytes: Optional[int] = None,
    schedule: Optional[BucketSchedule] = None,
    hier_stages="auto",
    groups=None,
):
    """The dual of :func:`bucketed_reduce_scatter`: per-leaf shard
    slices → full leaves with ``like``'s shapes, as N independent
    per-bucket all-gathers (concat member shards → ONE collective per
    bucket → per-leaf columns → unpad/reshape). The schedule is keyed
    on ``like``'s (full) leaf geometry, so a matched reduce-scatter /
    all-gather pair shares ONE cached schedule.

    ``groups`` mirrors :func:`bucketed_reduce_scatter`'s local-phase
    contract: shards are the ``[cols = ceil(size/L)]`` group-position
    slices and every gather runs inside its ``axis_index_groups``
    group only (``hier_stages`` ignored).

    ``residuals`` (tree in SHARD geometry — leaf ``[cols]``) is the
    error-feedback carry for lossy buckets on this leg: it joins the
    shard signal before the wire; returns ``(full, new_residuals)``.
    Buckets whose member dtypes diverge fall back to per-leaf fp32
    gathers (an inner transform that changes dtype per leaf)."""
    if n_buckets is None:
        n_buckets = default_buckets() or 1
    if min_bucket_bytes is None:
        min_bucket_bytes = default_min_bytes()
    if groups is not None:
        n = len(groups[0])
        groups = [list(g) for g in groups]
        stages = None
    else:
        n = jax.lax.axis_size(axis_name)
        stages = _auto_stages(hier_stages, n)
    if residuals is not None:
        stages = None  # EF carries are defined against the flat wire
    hier_L = None if stages is None else len(stages[0][0])
    s_leaves, s_def = jax.tree_util.tree_flatten(shards)
    l_leaves = s_def.flatten_up_to(like)
    nonscalar = [
        i for i, l in enumerate(l_leaves)
        if np.ndim(l) > 0 and not _is_float0(l)
    ]
    if schedule is None:
        schedule = schedule_for(
            [l_leaves[i] for i in nonscalar], s_def,
            n_buckets, min_bucket_bytes,
        )
    r_leaves = (
        s_def.flatten_up_to(residuals) if residuals is not None else None
    )
    out: list = [None] * len(s_leaves)
    res_out: list = [None] * len(s_leaves)
    in_schedule = set(nonscalar)
    for i in range(len(s_leaves)):
        if i not in in_schedule:
            out[i] = s_leaves[i]  # replicated scalars pass through
            if r_leaves is not None:
                res_out[i] = r_leaves[i]
    for b, idxs in enumerate(schedule.buckets):
        mem = [s_leaves[nonscalar[j]] for j in idxs]
        if len({m.dtype for m in mem}) > 1:
            for j in idxs:
                i = nonscalar[j]
                l = l_leaves[i]
                full = jax.lax.all_gather(
                    s_leaves[i], axis_name, axis=0,
                    axis_index_groups=groups,
                ).reshape(-1)
                size = int(np.prod(np.shape(l), dtype=np.int64))
                out[i] = (
                    full[:size].reshape(np.shape(l))
                    .astype(s_leaves[i].dtype)
                )
                if r_leaves is not None:
                    res_out[i] = r_leaves[i]
            continue
        cols = [m.shape[0] for m in mem]
        buf = mem[0] if len(mem) == 1 else jnp.concatenate(mem)
        if r_leaves is not None:
            rparts = [
                r_leaves[nonscalar[j]].astype(buf.dtype) for j in idxs
            ]
            buf = buf + (
                rparts[0] if len(rparts) == 1
                else jnp.concatenate(rparts)
            )
        if stages is not None:
            bw = resolve_wire(
                wire, int(schedule.bucket_bytes[b]) // hier_L,
                itemsize=mem[0].dtype.itemsize,
                key=("zero_ag", b, buf.shape[0]), hop="inter",
            )
            bseed = seed * schedule.n_buckets + b
            full = traced.hierarchical_allgather(
                buf, axis_name=axis_name, stages=stages,
                intra_wire="bf16" if bw == "bf16" else "fp32",
                inter_wire=bw, seed=bseed, block_size=wire_block,
            )
            off = 0
            for j, c in zip(idxs, cols):
                i = nonscalar[j]
                l = l_leaves[i]
                size = int(np.prod(np.shape(l), dtype=np.int64))
                out[i] = (
                    full[:, off : off + c]
                    .reshape(-1)[:size]
                    .reshape(np.shape(l))
                    .astype(s_leaves[i].dtype)
                )
                off += c
            continue
        bw = resolve_wire(
            wire, int(schedule.bucket_bytes[b]),
            itemsize=mem[0].dtype.itemsize,
            key=("zero_ag", b, buf.shape[0]),
        )
        new_r = None
        if bw == "int8":
            bseed = seed * schedule.n_buckets + b
            if r_leaves is not None:
                full, new_r = traced.quantized_allgather(
                    buf, axis_name=axis_name, seed=bseed,
                    block_size=wire_block, return_residual=True,
                    groups=groups,
                )
            else:
                full = traced.quantized_allgather(
                    buf, axis_name=axis_name, seed=bseed,
                    block_size=wire_block, groups=groups,
                )
        else:
            wire_buf = buf.astype(jnp.bfloat16) if bw == "bf16" else buf
            full = jax.lax.all_gather(
                wire_buf, axis_name, axis=0, axis_index_groups=groups,
            ).astype(buf.dtype)  # [n, C]
            if r_leaves is not None:
                new_r = (
                    buf - wire_buf.astype(buf.dtype)
                    if bw == "bf16"
                    else jnp.zeros_like(buf)
                )
        off = 0
        for j, c in zip(idxs, cols):
            i = nonscalar[j]
            l = l_leaves[i]
            size = int(np.prod(np.shape(l), dtype=np.int64))
            out[i] = (
                full[:, off : off + c]
                .reshape(-1)[:size]
                .reshape(np.shape(l))
                .astype(s_leaves[i].dtype)
            )
            if r_leaves is not None:
                res_out[i] = new_r[off : off + c].astype(
                    r_leaves[i].dtype
                )
            off += c
    gathered = jax.tree_util.tree_unflatten(s_def, out)
    if residuals is None:
        return gathered
    return gathered, jax.tree_util.tree_unflatten(s_def, res_out)


def overlap_boundary(
    tree,
    op=Average,
    average: Optional[bool] = None,
    n_buckets: Optional[int] = None,
    compression: Compressor = Compression.none,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
    seed=0,
    mask=None,
    min_bucket_bytes: Optional[int] = None,
    hier_stages="auto",
):
    """The in-backprop boundary marker: identity on the forward; on the
    backward, the cotangent pytree leaves through
    :func:`bucketed_allreduce`.

    Pass the model parameters through this before using them::

        def loss(params, batch):
            params = hvd.overlap_boundary(params, overlap_buckets=4)
            ...

    ``jax.grad`` of such a loss returns gradients that were ALREADY
    reduced during backprop — each bucket's collective sits in the
    backward dataflow at the point its last member gradient
    materializes, which is the reference's autograd-hook overlap
    expressed as compiler-visible dataflow. The custom_vjp body is
    inlined at trace time, so XLA sees N independent collectives, not
    an opaque call."""
    kw = dict(
        op=op,
        average=average,
        n_buckets=n_buckets,
        compression=compression,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        process_set=process_set,
        axis_name=axis_name,
        seed=seed,
        mask=mask,
        min_bucket_bytes=min_bucket_bytes,
        hier_stages=hier_stages,
    )

    @jax.custom_vjp
    def _boundary(t):
        return t

    def _fwd(t):
        return t, None

    def _bwd(_, ct):
        return (bucketed_allreduce(ct, **kw),)

    _boundary.defvjp(_fwd, _bwd)
    return _boundary(tree)
