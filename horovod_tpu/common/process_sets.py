"""Process sets: named subsets of ranks with their own collective scope.

TPU-native equivalent of the reference's process-set table
(ref: horovod/common/process_set.cc/.h + horovod/common/process_sets.py [V],
SURVEY.md §2.1): where the reference allocates a sub-communicator (MPI comm /
NCCL comm) per set, we allocate (a) a sub-mesh over the set's chips for eager
dispatch and (b) ``axis_index_groups`` for traced collectives — XLA lowers
those to collectives over exactly the set's ICI links.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence


class ProcessSet:
    """A named subset of ranks. ``process_set_id`` 0 is the global set."""

    def __init__(self, ranks: Sequence[int]):
        self.ranks: List[int] = sorted(int(r) for r in ranks)
        if len(set(self.ranks)) != len(self.ranks):
            raise ValueError(f"duplicate ranks in process set: {ranks}")
        self.process_set_id: Optional[int] = None  # assigned at registration

    @property
    def size(self) -> int:
        return len(self.ranks)

    def included(self, rank: int) -> bool:
        return rank in self.ranks

    def rank_in_set(self, rank: int) -> int:
        """Position of a global rank within this set (ref: the per-set rank
        remap in process_set.cc [V])."""
        try:
            return self.ranks.index(rank)
        except ValueError:
            raise ValueError(f"rank {rank} not in process set {self.ranks}")

    def axis_index_groups(self, world_size: int):
        """Groups argument for lax.psum & friends restricting the collective
        to this set. Ranks outside the set form singleton groups (they
        participate in the program but reduce with themselves only)."""
        if self.size == world_size:
            return None
        groups = [list(self.ranks)]
        for r in range(world_size):
            if r not in self.ranks:
                groups.append([r])
        return groups

    def __repr__(self) -> str:
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


def member_tables(world: int, ranks):
    """(member_mask[world], member_position[world]) numpy lookup tables
    for masked full-axis collectives over a process set — the one shared
    construction behind the fusion executors and adasum_allreduce's
    gather+tree path (XLA's TPU lowering rejects unequal replica groups,
    so subset collectives are expressed as full-axis programs indexed by
    these tables)."""
    import numpy as np

    member = np.zeros(world, dtype=bool)
    pos = np.zeros(world, dtype=np.int32)
    for i, rk in enumerate(ranks):
        member[rk] = True
        pos[rk] = i
    return member, pos


def warn_nonmember_controller(op_name: str, process_set) -> None:
    """Warn when a framework-shim collective is called with a process
    set that EXCLUDES rank 0 (ADVICE r3): under the single-controller
    model the shim caller is rank 0, so its tensor passes through
    unchanged — the reference errors for non-member callers, and
    silent pass-through can mask misuse. The contract is documented in
    docs/api.md ("Process sets under the single controller")."""
    if (
        process_set is not None
        and process_set.process_set_id != 0
        and 0 not in process_set.ranks
    ):
        import warnings

        warnings.warn(
            f"{op_name} over a process set that excludes rank 0: under "
            "the single-controller model this caller IS rank 0, so its "
            "tensor passes through unchanged (the exchange still "
            "happens among the members' rows). The reference errors "
            "for non-member callers — if you relied on that, check "
            "process_set.ranks before calling. See docs/api.md "
            "'Process sets under the single controller'.",
            stacklevel=3,
        )


class ProcessSetTable:
    """Registry mapping ids → ProcessSet, id 0 = global
    (ref: ProcessSetTable in horovod/common/process_set.h [V])."""

    def __init__(self, world_size: int):
        self._lock = threading.Lock()
        self._world_size = world_size
        self._by_id: Dict[int, ProcessSet] = {}
        self._next_id = 0
        global_set = ProcessSet(range(world_size))
        self.register(global_set)  # gets id 0

    @property
    def global_set(self) -> ProcessSet:
        return self._by_id[0]

    def register(self, ps: ProcessSet) -> ProcessSet:
        with self._lock:
            for existing in self._by_id.values():
                if existing.ranks == ps.ranks:
                    return existing
            bad = [r for r in ps.ranks if not 0 <= r < self._world_size]
            if bad:
                raise ValueError(
                    f"ranks {bad} out of range for world size {self._world_size}"
                )
            ps.process_set_id = self._next_id
            self._next_id += 1
            self._by_id[ps.process_set_id] = ps
            return ps

    def remove(self, ps: ProcessSet) -> None:
        with self._lock:
            if ps.process_set_id == 0:
                raise ValueError("cannot remove the global process set")
            self._by_id.pop(ps.process_set_id, None)
            ps.process_set_id = None

    def get(self, process_set_id: int) -> ProcessSet:
        with self._lock:
            return self._by_id[process_set_id]

    def ids(self) -> List[int]:
        with self._lock:
            return sorted(self._by_id)
