"""Pipeline parallelism over the 'pp' mesh axis: GPipe forward (demo)
and a 1F1B training schedule with bounded activation memory.

Absent from the reference (SURVEY.md §2.6); built TPU-first: stages are
chips along the 'pp' mesh axis, activations hop stage→stage with
`ppermute`, and the schedules are `lax.scan`s over STATIC tick tables —
fully static control flow, so XLA sees one compiled program per stage
and overlaps each hop with compute.

Two schedules:

* `gpipe` — fill/drain forward-only scan. Differentiating through it
  checkpoints every tick's carry, so its backward holds O(n_micro)
  activations: fine as a demo / for inference, NOT the production
  training path (VERDICT r4 Weak #6).
* `pipeline_1f1b` — the training schedule. Combined-op 1F1B
  (PipeDream-flush dataflow; a stage may run one forward AND one
  backward in the same tick): explicit per-stage backward via
  `jax.vjp` recompute from a stash of STAGE INPUTS, so the activation
  live-set is O(pp) microbatch inputs per stage (<= the 2·pp+1
  in-flight window) — bounded by the pipeline depth, never by
  n_micro. Returns (loss, per-stage grads) directly; nothing
  differentiates through the scan.

Per-device code for use inside shard_map: every chip runs the same
scan; chip s applies its own stage parameters. The classic bubble is
(pp-1)/(n_micro+pp-1) for GPipe and the same fill+drain term for 1F1B;
callers pick n_micro >> pp to amortize it.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def gpipe(
    stage_fn: Callable,
    stage_params,
    x_micro,
    axis_name: str = "pp",
):
    """Run microbatches through the pipeline.

    stage_fn(params, x) -> y: this chip's stage (shapes preserved).
    stage_params: this chip's stage parameters (pp-sharded pytree leaf(s)).
    x_micro: [n_micro, ...] microbatched input. Only stage 0's copy is
        consumed; other stages may pass the same array (ignored).

    Returns [n_micro, ...] outputs, valid on the LAST stage (other stages
    return zeros) — broadcast back with a psum or collective if every
    stage needs them.
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    total = n_micro + pp - 1  # fill + drain
    micro_shape = x_micro.shape[1:]

    # Send each stage's output to the next stage; the wrap-around edge
    # (last → 0) carries drained values nobody reads.
    perm = [(j, (j + 1) % pp) for j in range(pp)]

    def step(carry, t):
        out_acc = carry["out"]
        prev_act = carry["act"]  # activation received from previous stage
        # Stage 0 injects microbatch t (zeros once drained); others use
        # what arrived over the ring.
        inject = jnp.where(
            t < n_micro,
            lax.dynamic_index_in_dim(
                x_micro, jnp.minimum(t, n_micro - 1), keepdims=False
            ),
            jnp.zeros(micro_shape, x_micro.dtype),
        )
        x_in = jnp.where(stage == 0, inject, prev_act)
        y = stage_fn(stage_params, x_in)
        # Last stage: microbatch index t - (pp-1) completes at step t.
        done_idx = t - (pp - 1)
        is_done = jnp.logical_and(done_idx >= 0, stage == pp - 1)
        out_acc = lax.cond(
            is_done,
            lambda acc: lax.dynamic_update_index_in_dim(
                acc, y, jnp.maximum(done_idx, 0), axis=0
            ),
            lambda acc: acc,
            out_acc,
        )
        act_next = lax.ppermute(y, axis_name, perm)
        return {"out": out_acc, "act": act_next}, None

    init = {
        "out": jnp.zeros((n_micro,) + micro_shape, x_micro.dtype),
        "act": jnp.zeros(micro_shape, x_micro.dtype),
    }
    final, _ = lax.scan(step, init, jnp.arange(total))
    return final["out"]


# --------------------------------------------------------------- 1F1B


def _default_in_flight(pp: int) -> int:
    """Per-global-stage in-flight bound. 2·pp+1 is the full-throughput
    window of the combined-op model (a backward wave returns after
    ~2·hops ticks), measured to saturate the greedy schedule: stage
    time n+2(pp-1)+O(1) ticks vs ~2n under the classic pp bound —
    e.g. pp=4, n=32: 38 vs 59 ticks. Live inputs stay O(pp) (≤ ~1.5·pp
    per device measured), never O(n_micro)."""
    return 2 * pp + 1


def _build_1f1b_schedule(
    pp: int, n_micro: int, v: int = 1, cap: int = None
):
    """Static 1F1B tick tables (numpy, computed at trace time — pp,
    n_micro, and v are static). Combined-op variant: a DEVICE may do
    one forward AND one backward in the same tick (uniform compute per
    tick; see pipeline_1f1b).

    ``v`` > 1 is the Megatron-style INTERLEAVED schedule: v chunks of
    the layer stack per device, global stage g = c·pp + s living on
    device s = g % pp as chunk c = g // pp — acts still hop one device
    forward (the chunk boundary pp-1 -> 0 rides the same ring wrap),
    cotangents one device back. Measured effect (schedule simulator,
    stage-time = T/v ticks of full-stage work): pp=8, n=64: 78 (v=1)
    -> 75 (v=2) -> 73.5 (v=4) vs ideal 64 — a modest further fill
    reduction on top of the in-flight window (see _default_in_flight),
    bought with v-fold stash memory. The 1-tick-per-hop combined-op
    model cannot reach Megatron's (pp-1)/v fill exactly.

    Greedy under the 1F1B constraints, per global stage g:

    * F(g, m) needs F(g-1, m) from an earlier tick (act over the ring)
      and < cap microbatches in flight on g (the memory bound;
      default _default_in_flight(pp) = 2·pp+1);
    * B(g, m) needs B(g+1, m) from an earlier tick (cotangent over the
      ring), except the LAST global stage, which may do F(m) and B(m)
      in the SAME tick (its dy comes from its own loss, computed
      in-tick).

    Per tick a device picks its ready F and B by Megatron's wave order
    (microbatch group m//pp, then chunk — ascending for F, deepest
    first for B).

    Returns dict of int32 [T, pp] arrays:
      do_f/do_b (op masks), f_idx/b_idx (microbatch indices),
      f_c/b_c (chunk indices), ra_v/ra_s/ra_c (receive-activation
      valid + stash slot + chunk), rc_v/rc_s/rc_c (same, cotangent).
    """
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")
    if v < 1:
        raise ValueError("virtual_stages must be >= 1")
    if cap is None:
        cap = _default_in_flight(pp)
    N = v * pp  # global stages
    S = cap + 1  # stash slots/chunk; in-flight <= cap consecutive
    t_f = [[None] * n_micro for _ in range(N)]
    t_b = [[None] * n_micro for _ in range(N)]
    next_f = [0] * N
    next_b = [0] * N
    rows = []
    t = 0
    while any(nb < n_micro for nb in next_b):
        row = {
            k: [0] * pp
            for k in ("do_f", "f_idx", "f_c", "do_b", "b_idx", "b_c")
        }
        for s in range(pp):
            f_cands = []
            for c in range(v):
                g = c * pp + s
                m = next_f[g]
                if m >= n_micro:
                    continue
                if next_f[g] - next_b[g] >= cap:
                    continue
                if g > 0 and (
                    t_f[g - 1][m] is None or t_f[g - 1][m] >= t
                ):
                    continue
                f_cands.append(((m // pp, c, m % pp), m, c, g))
            if f_cands:
                _key, m, c, g = min(f_cands)
                row["do_f"][s] = 1
                row["f_idx"][s] = m
                row["f_c"][s] = c
                t_f[g][m] = t
                next_f[g] += 1
            b_cands = []
            for c in range(v):
                g = c * pp + s
                m = next_b[g]
                if m >= next_f[g]:
                    continue
                if g == N - 1:
                    if t_f[g][m] is None or t_f[g][m] > t:
                        continue  # same-tick F -> B allowed
                elif t_b[g + 1][m] is None or t_b[g + 1][m] >= t:
                    continue
                b_cands.append(((m // pp, -c, m % pp), m, c, g))
            if b_cands:
                _key, m, c, g = min(b_cands)
                row["do_b"][s] = 1
                row["b_idx"][s] = m
                row["b_c"][s] = c
                t_b[g][m] = t
                next_b[g] += 1
        rows.append(row)
        t += 1
        if t > 6 * (n_micro * v + N) + 16:
            raise AssertionError("1F1B schedule failed to converge")

    T = len(rows)
    out = {
        k: np.zeros((T, pp), np.int32)
        for k in (
            "do_f", "f_idx", "f_c", "do_b", "b_idx", "b_c",
            "ra_v", "ra_s", "ra_c", "rc_v", "rc_s", "rc_c",
        )
    }
    for t, row in enumerate(rows):
        for k in ("do_f", "f_idx", "f_c", "do_b", "b_idx", "b_c"):
            out[k][t] = row[k]
    # receive gating: what arrived over the ring THIS tick is whatever
    # the neighbor sent LAST tick. Device math: stage g+1 always lives
    # on device (g+1) % pp — one fwd hop — including the chunk-boundary
    # wrap pp-1 -> 0; symmetrically for cotangents.
    for t in range(1, T):
        prev = rows[t - 1]
        for s in range(pp):
            sprev = (s - 1) % pp
            if prev["do_f"][sprev]:
                g = prev["f_c"][sprev] * pp + sprev
                if g + 1 < N:  # the last stage sends nothing onward
                    out["ra_v"][t, s] = 1
                    out["ra_s"][t, s] = prev["f_idx"][sprev] % S
                    out["ra_c"][t, s] = (g + 1) // pp
            snext = (s + 1) % pp
            if prev["do_b"][snext]:
                g = prev["b_c"][snext] * pp + snext
                if g > 0:  # stage 0 sends no cotangent onward
                    out["rc_v"][t, s] = 1
                    out["rc_s"][t, s] = prev["b_idx"][snext] % S
                    out["rc_c"][t, s] = (g - 1) // pp
    return out


def pipeline_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    x_micro,
    y_micro,
    axis_name: str = "pp",
    loss_params=None,
    return_dx: bool = False,
    virtual_stages: int = 1,
    max_in_flight: int = None,
    loss_collective_free: bool = False,
):
    """1F1B pipeline TRAINING step: returns ``(loss, grads)`` directly.

    The production PP schedule (VERDICT r4 item 7): unlike
    differentiating through `gpipe` — whose scan-of-activations
    backward checkpoints O(n_micro) activations per stage — this runs
    an explicit per-stage backward inside the same scan. Each stage
    stashes only its microbatch INPUTS (<= max_in_flight+1 slots,
    default 2·pp+2) and recomputes its forward in `jax.vjp` at
    backward time (recompute beats storing on an HBM-bound chip — the
    same trade the flash kernels make), so the activation live-set is
    O(pp) — bounded by the pipeline depth, never by n_micro. Nothing
    differentiates through the scan: the returned grads ARE the
    backward.

    stage_fn(params, x) -> y: this chip's stage; activation shapes are
        preserved across stages (the `gpipe` contract). May contain
        collectives over OTHER mesh axes (tp/dp): every tick runs
        stage_fn and its vjp unconditionally (idle ticks compute on
        zeros and their effects are masked out with `where`-selects),
        so collectives inside stage_fn stay uniform across the mesh.
    loss_fn(y, target) -> scalar: evaluated on the LAST stage's output
        per microbatch; its value-grad seeds the backward. With
        ``loss_params`` given, the signature becomes
        ``loss_fn(loss_params, y, target)`` — a parameterized model
        TAIL (e.g. final norm + LM head + loss) whose gradients are
        returned too. Like stage_fn it runs unconditionally every
        tick, so collectives inside are mesh-uniform.
    stage_params: this chip's stage parameters (pp-sharded pytree).
        With ``virtual_stages=v > 1`` every leaf carries a leading [v]
        chunk axis: chunk c on device s is GLOBAL stage c·pp + s (the
        Megatron interleaved layout), and the returned grads keep the
        [v] axis.
    x_micro, y_micro: [n_micro, ...] microbatched inputs/targets. Only
        stage 0 consumes x_micro and only the last stage consumes
        y_micro; other stages may pass the same arrays (ignored).
    virtual_stages: interleaved-1F1B depth v. v·pp global stages ride
        the same two ppermute rings (the chunk boundary wraps pp-1 ->
        0); shrinks the fill/drain further (measured in the schedule
        simulator, pp=8 n=64: stage-time 78 -> 75 -> 73.5 ticks for
        v=1/2/4) at the cost of a v-fold larger input stash.
    max_in_flight: per-global-stage microbatch window (default
        2·pp+1 — the full-throughput window, see _default_in_flight;
        set pp to trade ~35%% throughput for the minimal stash).
    loss_collective_free: DECLARE that ``loss_fn`` contains no
        collectives (no psum/all_gather/ppermute over ANY mesh axis —
        a plain elementwise/softmax loss, or a tail whose tp
        collectives were hoisted out). The tail evaluation then runs
        under a real ``lax.cond`` on the per-device schedule bit
        instead of compute-then-mask: only the device holding the
        FINAL global stage, and only on ticks where it actually
        finishes a microbatch, pays the loss forward+backward. This
        deletes the T·(pp-1) redundant tail evaluations of the
        uniform-tick model (advisor r5 finding; at n_micro = pp the
        masked tail burned ~4x the useful tail FLOPs) — see
        docs/perf.md §"1F1B tail FLOPs". The declaration is a
        CONTRACT, not detected: a collective inside ``loss_fn`` under
        this flag makes devices diverge on a collective call and the
        step deadlocks/miscompiles; leave False (the mesh-uniform
        default) whenever in doubt. ``stage_fn`` is unaffected — its
        tp/dp collectives stay legal either way.
    return_dx: also return d(loss)/d(x_micro) — the input cotangents,
        [n_micro, ...], valid on STAGE 0 only (zeros elsewhere; psum
        over the axis masked to stage 0 to broadcast) — for a
        differentiable HEAD in front of the pipeline (embeddings).
        This buffer is O(n_micro) like x_micro itself; the bounded-
        memory claim concerns per-LAYER activations, which stay <= pp.

    Returns (loss, grads[, loss_grads][, dx_micro]) by position:
      loss — mean microbatch loss, identical on every stage (psum'd).
      grads — THIS stage's parameter gradients of that mean loss
        (pp-sharded like stage_params; combine over dp with the usual
        allreduce).
      loss_grads — gradients for loss_params (only when loss_params is
        given); accumulated on the last stage and psum-broadcast so
        every stage holds them.
      dx_micro — only when return_dx=True.

    Bubble: fill+drain idle ticks ~ 2·pp/(n_micro + 2·pp); pick
    n_micro >> pp. Microbatch loss is averaged, matching a
    full-batch mean loss when loss_fn itself averages over its
    microbatch.

    Tail-FLOPs multiplier (ADVICE r5 — know what the masking costs):
    to keep collectives inside stage_fn mesh-uniform, EVERY tick runs
    one full forward AND one full vjp on every stage — idle ticks
    compute on zeros and their effects are `where`-masked out, but the
    FLOPs are really spent. One step therefore executes T·pp stage
    evaluations (T = schedule length ≈ n_micro·v + O(pp) fill/drain
    ticks, each a fwd+bwd pair on all pp stages) against the
    n_micro·v·pp evaluations the math needs: compute overhead ≈
    T/(n_micro·v), i.e. ~1 + O(pp/n_micro) — the same n_micro >> pp
    regime that shrinks the bubble also amortizes the masked tail.
    At small n_micro the tail dominates: n_micro = pp burns roughly
    4× the useful FLOPs. This is a deliberate trade (uniformity lets
    tp/dp collectives live inside stage_fn; recompute keeps the
    activation live-set O(pp)) — see docs/perf.md §"1F1B tail FLOPs"
    for the measured framing.
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    v = int(virtual_stages)
    cap = (
        _default_in_flight(pp) if max_in_flight is None else max_in_flight
    )
    if cap < 1:
        raise ValueError(f"max_in_flight must be >= 1, got {cap}")
    S = cap + 1
    sched = _build_1f1b_schedule(pp, n_micro, v, cap)
    T = sched["do_f"].shape[0]
    micro_shape = x_micro.shape[1:]
    dtype = x_micro.dtype
    # ONE stacked [T, K, pp] table: the scan body gathers a single
    # [K, pp] row per tick instead of 12 separate dynamic slices
    keys = tuple(sorted(sched))
    table = jnp.asarray(
        np.stack([sched[k] for k in keys], axis=1)
    )

    # normalize to the chunked form: leaves carry a leading [v] axis
    chunked_params = (
        stage_params
        if v > 1
        else jax.tree.map(lambda p: jnp.asarray(p)[None], stage_params)
    )

    fwd_perm = [(j, (j + 1) % pp) for j in range(pp)]
    bwd_perm = [(j, (j - 1) % pp) for j in range(pp)]
    is_last = stage == pp - 1  # device holding the final global stage

    def idx(arr, i):
        return lax.dynamic_index_in_dim(arr, i, keepdims=False)

    def upd(arr, val, i):
        return lax.dynamic_update_index_in_dim(arr, val, i, axis=0)

    # v == 1 is the common path (the composed transformer): chunk
    # indices are statically 0 there, so use static slices instead of
    # per-tick dynamic indexing on singleton axes
    if v == 1:
        def idx2(arr, c, i):  # [1, S, ...] -> [...]
            return idx(arr[0], i)

        def upd2(arr, val, c, i):
            return upd(arr[0], val, i)[None]

        def chunk_of(tree_, c):
            return jax.tree.map(lambda p: p[0], tree_)

        def acc_chunk(acc, d, c, cond):
            """acc[c] += d where cond (chunk axis static at v=1)."""
            return jax.tree.map(
                lambda a, dd: a + jnp.where(
                    cond, dd, jnp.zeros_like(dd)
                )[None],
                acc,
                d,
            )
    else:
        def idx2(arr, c, i):  # [v, S, ...] -> [...]
            return idx(idx(arr, c), i)

        def upd2(arr, val, c, i):
            return upd(arr, upd(idx(arr, c), val, i), c)

        def chunk_of(tree_, c):
            return jax.tree.map(lambda p: idx(p, c), tree_)

        def acc_chunk(acc, d, c, cond):
            return jax.tree.map(
                lambda a, dd: masked_set(a, idx(a, c) + dd, c, cond),
                acc,
                d,
            )

    def masked_set(arr, val, i, cond):
        """arr[i] = val where cond, else unchanged (read-modify-write
        keeps the scan carry shape-stable)."""
        return upd(arr, jnp.where(cond, val, idx(arr, i)), i)

    def masked_set2(arr, val, c, i, cond):
        return upd2(arr, jnp.where(cond, val, idx2(arr, c, i)), c, i)

    def step(carry, t):
        vals = idx(table, t)[:, stage]  # [K]
        row = {k: vals[j] for j, k in enumerate(keys)}

        # ring exchanges — unconditional, every tick (receivers gate)
        recv_a = lax.ppermute(carry["sent_a"], axis_name, fwd_perm)
        recv_c = lax.ppermute(carry["sent_c"], axis_name, bwd_perm)
        inbox_a = masked_set2(
            carry["inbox_a"], recv_a, row["ra_c"], row["ra_s"],
            row["ra_v"] == 1,
        )
        inbox_c = masked_set2(
            carry["inbox_c"], recv_c, row["rc_c"], row["rc_s"],
            row["rc_v"] == 1,
        )

        # ---- forward micro-op (masked when not scheduled)
        do_f = row["do_f"] == 1
        f_c = row["f_c"]
        f_slot = row["f_idx"] % S
        # global stage of this op: f_c*pp + stage; stage 0 chunk 0
        # consumes the pipeline input
        first_f = jnp.logical_and(stage == 0, f_c == 0)
        last_f = jnp.logical_and(is_last, f_c == v - 1)
        x_in = jnp.where(
            first_f,
            idx(x_micro, row["f_idx"]),
            idx2(inbox_a, f_c, f_slot),
        )
        y = stage_fn(chunk_of(chunked_params, f_c), x_in)
        tgt = idx(y_micro, row["f_idx"])
        if loss_params is None:
            def _tail(yy, tg):
                return jax.value_and_grad(
                    lambda q: loss_fn(q, tg)
                )(yy)
        else:
            def _tail(yy, tg):
                l, (dlp, dy) = jax.value_and_grad(
                    lambda lp, q: loss_fn(lp, q, tg), argnums=(0, 1)
                )(loss_params, yy)
                return l, (dlp, dy)
        if loss_collective_free:
            # collective-free declaration: a REAL per-device branch —
            # non-final stages (and fill/drain ticks) skip the tail
            # fwd+bwd instead of computing it and masking the result.
            # Legal only because cond branches with no collectives may
            # diverge across devices under shard_map.
            tail_shapes = jax.eval_shape(_tail, y, tgt)
            tail_out = lax.cond(
                jnp.logical_and(do_f, last_f),
                _tail,
                lambda yy, tg: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), tail_shapes
                ),
                y,
                tgt,
            )
        else:
            tail_out = _tail(y, tgt)
        if loss_params is None:
            l_m, dy_m = tail_out
        else:
            l_m, (dlp_m, dy_m) = tail_out
        carry_lacc = carry.get("lacc")
        if loss_params is not None:
            take = jnp.logical_and(do_f, last_f)
            carry_lacc = jax.tree.map(
                lambda a, d: a + jnp.where(take, d, jnp.zeros_like(d)),
                carry_lacc,
                dlp_m,
            )
        stash_x = masked_set2(
            carry["stash_x"], x_in, f_c, f_slot, do_f
        )
        # dy is only ever read by the FINAL global stage's backward —
        # one [S] bank suffices; other chunks' dy writes are masked off
        stash_dy = masked_set(
            carry["stash_dy"],
            dy_m.astype(dtype),
            f_slot,
            jnp.logical_and(do_f, last_f),
        )
        loss = carry["loss"] + jnp.where(
            jnp.logical_and(do_f, last_f),
            l_m.astype(jnp.float32),
            0.0,
        )
        sent_a = jnp.where(do_f, y, carry["sent_a"])

        # ---- backward micro-op (masked when not scheduled)
        do_b = row["do_b"] == 1
        b_c = row["b_c"]
        b_slot = row["b_idx"] % S
        first_b = jnp.logical_and(stage == 0, b_c == 0)
        last_b = jnp.logical_and(is_last, b_c == v - 1)
        x_b = idx2(stash_x, b_c, b_slot)
        dy_b = jnp.where(
            last_b,
            idx(stash_dy, b_slot),
            idx2(inbox_c, b_c, b_slot),
        )
        _, pull = jax.vjp(
            stage_fn, chunk_of(chunked_params, b_c), x_b
        )
        dp, dx = pull(dy_b.astype(dtype))
        gacc = acc_chunk(carry["gacc"], dp, b_c, do_b)
        sent_c = jnp.where(do_b, dx, carry["sent_c"])

        out = {
            "inbox_a": inbox_a,
            "inbox_c": inbox_c,
            "stash_x": stash_x,
            "stash_dy": stash_dy,
            "sent_a": sent_a,
            "sent_c": sent_c,
            "gacc": gacc,
            "loss": loss,
        }
        if loss_params is not None:
            out["lacc"] = carry_lacc
        if return_dx:
            out["dx"] = masked_set(
                carry["dx"], dx, row["b_idx"],
                jnp.logical_and(do_b, first_b),
            )
        return out, None

    zeros_micro = jnp.zeros(micro_shape, dtype)
    init = {
        "inbox_a": jnp.zeros((v, S) + micro_shape, dtype),
        "inbox_c": jnp.zeros((v, S) + micro_shape, dtype),
        "stash_x": jnp.zeros((v, S) + micro_shape, dtype),
        "stash_dy": jnp.zeros((S,) + micro_shape, dtype),
        "sent_a": zeros_micro,
        "sent_c": zeros_micro,
        "gacc": jax.tree.map(jnp.zeros_like, chunked_params),
        "loss": jnp.zeros((), jnp.float32),
    }
    if loss_params is not None:
        init["lacc"] = jax.tree.map(jnp.zeros_like, loss_params)
    if return_dx:
        init["dx"] = jnp.zeros((n_micro,) + micro_shape, dtype)
    final, _ = lax.scan(step, init, jnp.arange(T))
    loss = lax.psum(final["loss"], axis_name) / n_micro
    grads = jax.tree.map(lambda g: g / n_micro, final["gacc"])
    if v == 1:  # drop the internal chunk axis (unchunked API)
        grads = jax.tree.map(lambda g: g[0], grads)
    result = [loss, grads]
    if loss_params is not None:
        # accumulated on the last stage only; broadcast so every stage
        # holds the tail grads (they're replicated over pp)
        result.append(
            jax.tree.map(
                lambda g: lax.psum(
                    jnp.where(is_last, g, jnp.zeros_like(g)),
                    axis_name,
                )
                / n_micro,
                final["lacc"],
            )
        )
    if return_dx:
        result.append(jax.tree.map(lambda g: g / n_micro, final["dx"]))
    return tuple(result)
