"""``import horovod_tpu.mxnet as hvd`` — the MXNet binding surface.

Parity with the reference's MXNet module (ref: horovod/mxnet/__init__.py
+ mpi_ops.py + functions.py [V] — SURVEY.md §2.4/§2.5): Gluon scripts
port by changing one import. The bridge is the same host-side design as
the torch shim: each NDArray crosses to numpy once (``.asnumpy()``),
rides the eager collective path (so tensor fusion, process sets, the
join mask, and the timeline all apply), and the XLA-reduced result
comes back through ``mx.nd.array``.

Duck-typing contract: mxnet itself is imported lazily and only for
constructing result arrays, so the module imports (and the op surface
runs) with any NDArray-shaped object exposing ``.asnumpy()``/``.shape``
/``.dtype`` and a module registered as ``mxnet`` providing
``nd.array``. MXNet reached EOL upstream; this shim keeps script
compatibility without making the framework depend on it (the earlier
out-of-scope decision in docs/design.md is superseded by this gated
surface).

Divergences (documented, same one-controller model as the torch shim):
- ``priority`` is accepted and ignored — the reference uses it to order
  MXNet-engine async ops (horovod/mxnet/mpi_ops.py [V]); here dispatch
  order is the fusion cycle's enqueue order.
- ops are synchronous: the reference returns immediately and lets the
  MXNet engine chain dependencies; there is no engine to chain here.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common.basics import (  # noqa: F401
    add_process_set,
    cross_rank,
    cross_size,
    global_process_set,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    remove_process_set,
    shutdown,
    size,
)
from ..common.process_sets import (  # noqa: F401
    ProcessSet,
    warn_nonmember_controller as _warn_nonmember_controller,
)
from ..ops import eager as _eager
from ..ops.reduction_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
)


def start_timeline(file_path, mark_cycles: bool = False) -> None:
    import horovod_tpu as _hvd

    _hvd.start_timeline(file_path, mark_cycles=mark_cycles)


def stop_timeline() -> None:
    import horovod_tpu as _hvd

    _hvd.stop_timeline()


def _mx():
    import mxnet

    return mxnet


def _to_numpy(tensor) -> np.ndarray:
    return tensor.asnumpy()


def _from_numpy(array: np.ndarray, like):
    """numpy → NDArray on the caller's context, preserving dtype."""
    mx = _mx()
    shape = tuple(np.shape(array))
    arr = np.ascontiguousarray(array)  # promotes 0-d to (1,)
    kwargs = {}
    ctx = getattr(like, "context", None)
    if ctx is not None:
        kwargs["ctx"] = ctx
    dtype = getattr(like, "dtype", None)
    if dtype is not None:
        kwargs["dtype"] = dtype
    out = mx.nd.array(arr, **kwargs)
    if tuple(out.shape) != shape:
        out = out.reshape(shape)
    return out


def _replicated_payload(tensor):
    """Single-controller payload: every rank contributes this process's
    tensor (same data model as the torch shim)."""
    return _eager.replicate(_to_numpy(tensor))


def _finish(result, like):
    row = np.asarray(_eager.first(result))
    like_shape = tuple(getattr(like, "shape", row.shape))
    if row.size == int(np.prod(like_shape)) and row.shape != like_shape:
        # 0-d scalars ride the fusion path as shape-(1,) payloads;
        # restore the caller's shape (same guard as the torch shim)
        row = row.reshape(like_shape)
    return _from_numpy(row, like)


def _copy_into(target, value_nd):
    target[:] = value_nd
    return target


# --------------------------------------------------------------- collectives


def allreduce(tensor, average=None, name=None, priority=0, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              process_set: Optional[ProcessSet] = None):
    """hvd.allreduce for NDArrays (ref: horovod/mxnet/mpi_ops.py
    allreduce [V]). `priority` accepted for compatibility (see module
    docstring)."""
    del priority
    _warn_nonmember_controller("allreduce", process_set)
    handle = _eager.allreduce_async(
        _replicated_payload(tensor), average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return _finish(handle.wait(), tensor)


def allreduce_(tensor, average=None, name=None, priority=0, op=None,
               prescale_factor=1.0, postscale_factor=1.0,
               process_set: Optional[ProcessSet] = None):
    """In-place spelling: writes the reduction back into `tensor` [V]."""
    out = allreduce(tensor, average=average, name=name, priority=priority,
                    op=op, prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set=process_set)
    return _copy_into(tensor, out)


def grouped_allreduce(tensors, average=None, name=None, priority=0, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set: Optional[ProcessSet] = None):
    """Atomic grouped allreduce (ref: grouped_allreduce [V]) — the group
    rides the fusion engine's indivisible-group machinery."""
    del priority
    _warn_nonmember_controller("grouped_allreduce", process_set)
    handles = _eager.grouped_allreduce_async(
        [_replicated_payload(t) for t in tensors],
        average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return [_finish(h.wait(), t) for h, t in zip(handles, tensors)]


def grouped_allreduce_(tensors, **kwargs):
    outs = grouped_allreduce(tensors, **kwargs)
    return [_copy_into(t, o) for t, o in zip(tensors, outs)]


def allgather(tensor, name=None, priority=0,
              process_set: Optional[ProcessSet] = None):
    """Concatenates along axis 0 across ranks (ref: allgather [V])."""
    del priority
    _warn_nonmember_controller("allgather", process_set)
    handle = _eager.allgather_async(
        _replicated_payload(tensor), name=name, process_set=process_set,
    )
    # eager allgather yields rank-major [world, n, ...]; the NDArray
    # contract concatenates along dim 0 (same post step as the torch shim)
    host = np.asarray(_eager.first(handle.wait()))
    return _from_numpy(host.reshape((-1,) + host.shape[2:]), tensor)


def broadcast(tensor, root_rank, name=None, priority=0,
              process_set: Optional[ProcessSet] = None):
    """hvd.broadcast (ref: broadcast [V])."""
    del priority
    _warn_nonmember_controller("broadcast", process_set)
    handle = _eager.broadcast_async(
        _replicated_payload(tensor), root_rank=root_rank, name=name,
        process_set=process_set,
    )
    return _finish(handle.wait(), tensor)


def broadcast_(tensor, root_rank, name=None, priority=0,
               process_set: Optional[ProcessSet] = None):
    out = broadcast(tensor, root_rank, name=name, priority=priority,
                    process_set=process_set)
    return _copy_into(tensor, out)


def alltoall(tensor, splits=None, name=None, priority=0,
             process_set: Optional[ProcessSet] = None):
    """hvd.alltoall with optional uneven 1-D `splits` (this rank's dim-0
    row counts per peer); returns (output, received_splits) when splits
    are given, like the reference (ref: alltoall [V]). Same replicated
    single-controller model as the torch shim's alltoall."""
    del priority
    _warn_nonmember_controller("alltoall", process_set)
    host = _to_numpy(tensor)
    if splits is not None:
        world = size()
        participants = (
            len(process_set.ranks)
            if process_set is not None and process_set.process_set_id != 0
            else world
        )
        splits_1d = [int(s) for s in np.asarray(
            splits.asnumpy() if hasattr(splits, "asnumpy") else splits
        ).reshape(-1).tolist()]
        if len(splits_1d) != participants:
            raise ValueError(
                f"splits has {len(splits_1d)} entries but the exchange "
                f"has {participants} participants"
            )
        if sum(splits_1d) != host.shape[0]:
            raise ValueError(
                f"splits sum to {sum(splits_1d)} but tensor dim0 is "
                f"{host.shape[0]}"
            )
        handle = _eager.alltoall_async(
            [host] * world, splits=[splits_1d] * world, name=name,
            process_set=process_set,
        )
        outputs, recv_splits = handle.wait()
        out = _from_numpy(np.array(outputs[0], copy=True), tensor)
        mx = _mx()
        return out, mx.nd.array(
            np.asarray(recv_splits[0], dtype=np.int32), dtype="int32"
        )
    handle = _eager.alltoall_async(
        _eager.replicate(host), name=name, process_set=process_set,
    )
    return _finish(handle.wait(), tensor)


def reducescatter(tensor, name=None, priority=0, op=None,
                  process_set: Optional[ProcessSet] = None):
    """hvd.reducescatter (ref: reducescatter [V])."""
    del priority
    _warn_nonmember_controller("reducescatter", process_set)
    handle = _eager.reducescatter_async(
        _replicated_payload(tensor), name=name, op=op,
        process_set=process_set,
    )
    return _finish(handle.wait(), tensor)


# ---------------------------------------------------------------- functions


def broadcast_parameters(params, root_rank: int = 0, prefix: str = "") -> None:
    """Broadcast a Gluon ``ParameterDict`` / plain dict of NDArrays from
    `root_rank` in place (ref: horovod/mxnet/functions.py
    broadcast_parameters [V]). Gluon Parameters are recognized by their
    ``list_data()``/``set_data()`` methods; plain NDArrays by
    ``asnumpy``. Keys are sorted so every rank walks the same order."""
    if params is None:
        return
    items = sorted(params.items()) if hasattr(params, "items") else sorted(
        enumerate(params)
    )
    for key, p in items:
        name = f"{prefix}{key}"
        if hasattr(p, "list_data") and hasattr(p, "set_data"):
            # gluon Parameter: broadcast the master copy, set_data fans
            # it out to every context
            data = p.list_data()[0]
            out = broadcast(data, root_rank, name=f"bp.{name}")
            p.set_data(out)
        elif hasattr(p, "asnumpy"):
            broadcast_(p, root_rank, name=f"bp.{name}")
        elif p is None:
            continue
        else:
            raise ValueError(
                f"broadcast_parameters: unsupported value for {name!r}: "
                f"{type(p).__name__}"
            )


# --------------------------------------------------------------- optimizers


class _DistOptMixin:
    """The Horovod half of DistributedOptimizer: allreduce each gradient
    before delegating update/update_multi_precision (ref:
    horovod/mxnet/__init__.py DistributedOptimizer [V]). Combined with
    ``mx.optimizer.Optimizer`` as a base when real mxnet is importable
    (so isinstance checks in gluon.Trainer / Module.init_optimizer
    accept it, like the reference's subclass), and used standalone for
    duck-typed optimizers."""

    def _hvd_init(self, optimizer, gradient_predivide_factor, num_groups,
                  op, process_set):
        op = Average if op is None else op
        if float(gradient_predivide_factor) != 1.0 and op is not Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average "
                "(ref parity)")
        self._optimizer = optimizer
        self._op = op
        self._predivide = float(gradient_predivide_factor)
        self._num_groups = int(num_groups)
        self._process_set = process_set

    def __getattr__(self, item):
        inner = self.__dict__.get("_optimizer")
        if inner is None:  # not yet _hvd_init'd (base __init__ probes)
            raise AttributeError(item)
        return getattr(inner, item)

    def __setattr__(self, name, value):
        # Real-mxnet callers poke public knobs straight onto the
        # optimizer object (Trainer sets rescale_grad per step); mirror
        # them onto the wrapped optimizer, whose update() consumes them.
        object.__setattr__(self, name, value)
        inner = self.__dict__.get("_optimizer")
        if inner is not None and not name.startswith("_"):
            try:
                setattr(inner, name, value)
            except Exception:
                pass

    def _reduce(self, grads, names):
        if self._predivide != 1.0:  # only reachable with op=Average
            pre = 1.0 / self._predivide
            post = self._predivide
        else:
            pre, post = 1.0, 1.0
        grads = list(grads)
        # num_groups > 0: split into that many fusion groups, like the
        # reference's grouped allreduce batching [V]; each group is one
        # atomic grouped_allreduce (0 = everything in one group)
        n_groups = max(1, min(self._num_groups, len(grads))) \
            if self._num_groups > 0 else 1
        out = []
        for chunk_idx in range(n_groups):
            chunk = grads[chunk_idx::n_groups]
            if not chunk:
                continue
            reduced = grouped_allreduce(
                chunk, op=self._op,
                name=names[chunk_idx] if chunk_idx < len(names) else None,
                prescale_factor=pre, postscale_factor=post,
                process_set=self._process_set,
            )
            out.append((chunk, reduced))
        for chunk, reduced in out:
            for g, r in zip(chunk, reduced):
                _copy_into(g, r)

    @staticmethod
    def _listify(index, weight, grad, state):
        if isinstance(index, (tuple, list)):
            return list(index), list(weight), list(grad), state
        return [index], [weight], [grad], state

    def update(self, index, weight, grad, state):
        idx, w, g, st = self._listify(index, weight, grad, state)
        self._reduce(g, [f"grad.{i}" for i in idx])
        return self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        idx, w, g, st = self._listify(index, weight, grad, state)
        self._reduce(g, [f"grad.{i}" for i in idx])
        return self._optimizer.update_multi_precision(
            index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def DistributedOptimizer(optimizer, gradient_predivide_factor: float = 1.0,
                         num_groups: int = 0, op=None,
                         process_set: Optional[ProcessSet] = None):
    """Factory (same call shape as the reference's class [V]): returns
    an ``mx.optimizer.Optimizer`` subclass instance when `optimizer` is
    a real mxnet Optimizer — so gluon.Trainer/Module isinstance checks
    pass — and a duck-typed wrapper otherwise."""
    if op is not None and op not in (Average, Sum, Adasum):
        raise ValueError(
            "DistributedOptimizer supports Average, Sum and Adasum")
    bases = (_DistOptMixin,)
    try:
        import mxnet as mx

        real_base = getattr(getattr(mx, "optimizer", None), "Optimizer",
                            None)
        if real_base is not None and isinstance(optimizer, real_base):
            bases = (_DistOptMixin, real_base)
    except Exception:
        pass

    cls = type("DistributedOptimizer", bases, {})
    # Deliberately do NOT run Optimizer.__init__: its kwarg defaults
    # (lr/wd/rescale_grad...) would land as instance attributes on the
    # wrapper and permanently shadow __getattr__ delegation to the
    # wrapped optimizer's real values (the reference subclass skips it
    # for the same reason [V]). isinstance checks only need the bases.
    inst = cls.__new__(cls)
    inst._hvd_init(optimizer, gradient_predivide_factor, num_groups, op,
                   process_set)
    return inst


def DistributedTrainer(params, optimizer, optimizer_params=None,
                       compression=None, gradient_predivide_factor=1.0,
                       process_set: Optional[ProcessSet] = None):
    """Gluon Trainer whose ``_allreduce_grads`` reduces over the mesh
    (ref: horovod/mxnet/__init__.py DistributedTrainer [V]).

    Implemented as a factory: the subclass of ``mx.gluon.Trainer`` is
    built at call time, so importing this module never requires mxnet.
    Like the reference, the loss scale is folded into the trainer's
    rescale_grad so ``trainer.step(batch_size)`` keeps its Gluon
    meaning per worker.
    """
    del compression  # fp16 wire compression: the fused path casts bf16
    mx = _mx()
    pset = process_set

    class _DistributedTrainer(mx.gluon.Trainer):
        def __init__(self):
            # optimizer_params forwards UNCHANGED: gluon.Trainer asserts
            # it is None when `optimizer` is an Optimizer instance, and
            # the reference forwards it verbatim too [V]
            super().__init__(
                params, optimizer, optimizer_params,
                kvstore=None,
            )
            # The reference rescales because its wire op is a Sum; this
            # shim reduces with Average, so Gluon's own rescale_grad
            # semantics (divide by step's batch_size) are already
            # per-worker-correct and _scale is left untouched [V].
            self._hvd_predivide = float(gradient_predivide_factor)

        def _allreduce_grads(self):
            grads, names = [], []
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for g in param.list_grad():
                        grads.append(g)
                        names.append(f"grad.{i}")
            if not grads:
                return
            if self._hvd_predivide != 1.0:
                pre = 1.0 / self._hvd_predivide
                post = self._hvd_predivide
            else:
                pre, post = 1.0, 1.0
            reduced = grouped_allreduce(
                grads, op=Average, name=names[0],
                prescale_factor=pre, postscale_factor=post,
                process_set=pset,
            )
            for g, r in zip(grads, reduced):
                _copy_into(g, r)

    return _DistributedTrainer()
