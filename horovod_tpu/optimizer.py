"""Distributed optimization: the DistributedOptimizer / GradientTape layer.

TPU-native re-design of the reference's per-framework optimizer wrappers
(ref: horovod/torch/optimizer.py `_DistributedOptimizer` — per-parameter
grad hooks firing async allreduces, `backward_passes_per_step` local
aggregation, op=Average/Sum/Adasum, `gradient_predivide_factor`;
horovod/tensorflow/__init__.py `DistributedOptimizer` +
`DistributedGradientTape` [V]; SURVEY.md §2.4, §3.2, §3.5).

The reference hooks autograd to overlap per-tensor allreduces with backprop.
Under XLA that overlap is the *compiler's* job: expressing the gradient
reduction inside the jitted step lets XLA schedule collectives against
backprop compute (latency hiding on ICI) with no hook machinery. So:

* ``DistributedOptimizer(opt)`` wraps any optax ``GradientTransformation``:
  its ``update`` compresses → allreduces → decompresses gradients before the
  inner transform. Use inside ``jit``/``shard_map`` over the world axis.
* ``backward_passes_per_step=k`` accumulates k micro-batch gradients
  locally and communicates once — the reference's local-aggregation
  feature, which on TPU also amortizes ICI latency.
* ``DistributedGradientTape`` parity is ``hvd.value_and_grad`` /
  ``hvd.grad``: autodiff + gradient allreduce in one call.
"""

from __future__ import annotations

import functools
import itertools
import warnings
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from .common import guard as _guard
from .common import telemetry as _telemetry
from .common.process_sets import ProcessSet
from .common.topology import WORLD_AXIS
from .ops import overlap, traced
from .ops.compression import Compression, Compressor
from .ops.reduction_ops import Adasum, Average, ReduceOp, Sum, resolve_op


def _allreduce_grads(
    grads,
    op: ReduceOp,
    compression,
    prescale_factor: float,
    postscale_factor: float,
    process_set: Optional[ProcessSet],
    axis_name: str,
    seed=0,
    residuals=None,
    groups=None,
):
    """Compress → allreduce → decompress, leaf-wise over the grad pytree.

    Equivalent of the reference's `_allreduce_grad_async` + synchronize
    loop (horovod/torch/optimizer.py [V]), except the 'async' part is
    XLA's static schedule rather than handles.

    Quantized-wire compressors (Compression.int8) can't go through the
    generic compress→psum→decompress shape — summing raw int8 wraps and
    each rank's scale differs — so they route to the quantized
    collective, which reduces after dequantization on every hop.
    """
    if getattr(compression, "quantized_wire", False):
        if process_set is not None and process_set.process_set_id != 0:
            raise NotImplementedError(
                "Compression.int8 over a process set is not supported; "
                "use fp16/bf16 compression or the global process set"
            )
        # Compression.hier_int8 on the traced/optimizer path: the real
        # two-level recipe (bf16 intra hops, int8 on the inter hop
        # only — the eager placement, no longer a flat degeneration)
        # whenever a slice split is resolvable for this axis. A
        # local-SGD local phase (groups=) has NO inter hop — the
        # quantized wire stays inside the slice instead.
        hier_stages = None
        if (
            groups is None
            and getattr(compression, "wire_format", None) == "int8_hier"
        ):
            from .common import topology as _topo

            hier_stages = _topo.hierarchy_stages(
                world=int(jax.lax.axis_size(axis_name)), mode="on"
            )

        def one_q(g, r=None):
            """One leaf through the quantized wire; with an error-
            feedback carry ``r`` (EF-SGD), last step's quantization
            error joins this step's wire signal and the new residual is
            returned alongside. One body for both paths so the
            prescale/postscale handling can't diverge.

            ``prescale_factor`` is handed to the collective, which
            folds it into the stage-1 wire scales — quantization is
            scale-invariant, so scaling n floats replaces a full HBM
            pre-multiply pass over the tensor (parity-tested against
            the two-pass form in test_fusion_quantized.py). The
            residual contract is input units: the carry joins the RAW
            gradient, before any scaling. A compressor that defines
            ``block_size`` (Compression.int8_block and descendants)
            gets block-wise wire scales on this path too."""
            block = getattr(compression, "block_size", None)
            if hier_stages is not None:
                x = g if r is None else g + r.astype(g.dtype)
                if r is None:
                    out = traced.hierarchical_allreduce_groups(
                        x, op=op, axis_name=axis_name,
                        stages=hier_stages, intra_wire="bf16",
                        inter_wire="int8", seed=seed, block_size=block,
                        prescale_factor=prescale_factor,
                    )
                    new_r = None
                else:
                    out, new_r = traced.hierarchical_allreduce_groups(
                        x, op=op, axis_name=axis_name,
                        stages=hier_stages, intra_wire="bf16",
                        inter_wire="int8", seed=seed, block_size=block,
                        prescale_factor=prescale_factor,
                        return_residual=True,
                    )
                    new_r = new_r.astype(r.dtype)
            elif r is None:
                out = traced.quantized_allreduce(
                    g, op=op, axis_name=axis_name, seed=seed,
                    prescale_factor=prescale_factor, block_size=block,
                    groups=groups,
                )
                new_r = None
            else:
                out, new_r = traced.quantized_allreduce(
                    g + r.astype(g.dtype), op=op, axis_name=axis_name,
                    seed=seed, return_residual=True,
                    prescale_factor=prescale_factor, block_size=block,
                    groups=groups,
                )
                # carry keeps its init dtype: a flip (e.g. bf16 params,
                # f32 grads) would change the state pytree mid-scan
                new_r = new_r.astype(r.dtype)
            if postscale_factor != 1.0:
                out = out * jnp.asarray(postscale_factor, out.dtype)
            return out, new_r

        if residuals is not None:
            # flatten rather than tree_map: grads pytrees containing
            # tuples/NamedTuples would collide with the (out, residual)
            # result pairs under an isinstance(tuple) is_leaf
            g_leaves, treedef = jax.tree_util.tree_flatten(grads)
            r_leaves = treedef.flatten_up_to(residuals)
            out_pairs = [
                one_q(g, r) for g, r in zip(g_leaves, r_leaves)
            ]
            reduced = jax.tree_util.tree_unflatten(
                treedef, [t[0] for t in out_pairs]
            )
            new_residuals = jax.tree_util.tree_unflatten(
                treedef, [t[1] for t in out_pairs]
            )
            return reduced, new_residuals

        return jax.tree_util.tree_map(lambda g: one_q(g)[0], grads)
    if residuals is not None:
        raise ValueError(
            "error_feedback requires a quantized-wire compression "
            "(Compression.int8); lossless/fp16 wires have no residual"
        )

    def one(g):
        wire, ctx = compression.compress(g)
        red = traced.allreduce(
            wire,
            op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            process_set=process_set,
            axis_name=axis_name,
            groups=groups,
        )
        return compression.decompress(red, ctx)

    return jax.tree_util.tree_map(one, grads)


class _AccumulationState(NamedTuple):
    inner: Any
    accum: Any  # running local gradient sum
    counter: jnp.ndarray  # micro-steps since last communication
    step: jnp.ndarray  # monotone update count — seeds stochastic rounding
    residual: Any = None  # error-feedback carry (quantized wire only)
    guard_skips: Any = None  # total non-finite skipped steps (guard on)
    guard_streak: Any = None  # CONSECUTIVE skips — escalation trigger
    # local-SGD round state (local_sgd_steps > 1 only; None leaves keep
    # plain jobs' state structure and checkpoints byte-stable):
    local_anchor: Any = None  # params at the last sync round
    local_residual: Any = None  # EF carry of the int8 inter wire


class LocalSGDGradientTransformation(NamedTuple):
    """An optax ``GradientTransformation`` plus the local-SGD sync
    round: ``sync(params, state) -> (new_params, new_state)`` is the
    SEPARATE traced reconciliation body — call it inside the same
    shard_map context as ``update`` but compile it as its OWN program
    (the local-phase step program must carry zero inter-slice replica
    groups; a ``lax.cond`` would bake the inter exchange into every
    step). Drive the cadence with :func:`horovod_tpu.local_sgd
    .maybe_sync`, which owns the retry/defer robustness contract."""

    init: Callable
    update: Callable
    sync: Callable
    local_sgd_steps: int = 1


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    named_parameters=None,  # accepted for API parity; names are pytree paths
    compression: Compressor = Compression.none,
    backward_passes_per_step: int = 1,
    op: Optional[ReduceOp] = None,
    gradient_predivide_factor: float = 1.0,
    average: Optional[bool] = None,
    prescale_factor: Optional[float] = None,
    postscale_factor: Optional[float] = None,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
    average_aggregated_gradients: bool = False,
    error_feedback: bool = False,
    overlap_buckets: Optional[int] = None,
    overlap_min_bytes: Optional[int] = None,
    grad_guard: Optional[bool] = None,
    guard_max_skips: Optional[int] = None,
    local_sgd_steps: Optional[int] = None,
    local_sgd_inter_wire: str = "int8",
    local_sgd_intra: Optional[int] = None,
) -> optax.GradientTransformation:
    """Wrap an optax transform with distributed gradient reduction
    (ref: hvd.DistributedOptimizer [V]).

    ``gradient_predivide_factor`` splits the averaging between pre- and
    post-division around the sum exactly like the reference (which uses it
    to keep fp16 sums in range): grads are multiplied by
    ``1/(size·f)`` before and ``f`` after... i.e. prescale=1/(size·f),
    postscale=f with op=Sum (ref: optimizer.py's predivide handling [V]).

    ``error_feedback=True`` (beyond parity; requires
    ``compression=Compression.int8``) carries each step's local
    quantization error into the next step's wire signal — EF-SGD, so
    the int8 wire's cumulative error stays bounded by a constant number
    of quanta instead of growing with the step count.

    ``overlap_buckets=N`` routes the exchange through the bucketed
    layer (``ops/overlap.py``): the gradient tree is partitioned into N
    size-balanced buckets in reverse production order and each bucket
    gets its OWN collective, so the compiled step carries N independent
    collectives XLA can schedule against remaining backward compute
    instead of one terminal exchange — the reference's autograd-hook
    overlap, recovered as compiler-visible dataflow. Bit-exact with the
    monolithic path for op=Sum fp32; within the per-bucket quantum
    bound for quantized wires (EF residuals, the prescale fold and
    block granularity are applied per bucket). ``None`` defers to
    ``HOROVOD_OVERLAP``/``HOROVOD_OVERLAP_BUCKETS``; 0 forces the
    monolithic path. Sum/Average only (Adasum's whole-tensor combine
    does not commute with bucket concat). For overlap of the exchange
    with the backward itself, prefer ``hvd.value_and_grad(...,
    overlap_buckets=N)`` — this wrapper only sees gradients after
    autodiff, so its buckets overlap each other and the update math.

    ``grad_guard=True`` (``None`` defers to ``HOROVOD_GUARD``) folds
    the non-finite sentinel into the compiled update
    (common/guard.py): one ``all(isfinite)`` scalar reduction per
    bucket (per leaf on the monolithic path) over the ALREADY-REDUCED
    gradients — replicated values, so the flag agrees across ranks
    with no extra collective — and a ``lax.cond`` that SKIPS the step
    when the flag trips: zero updates, inner state untouched, EF
    residuals kept at the last applied step's carry, the step counter
    still advancing (stochastic-rounding seeds never repeat). Each
    skip fires a callback counting ``guard.nonfinite_steps``; after
    ``guard_max_skips`` (``HOROVOD_GUARD_MAX_SKIPS``) CONSECUTIVE
    skips the escalation latches and ``State.commit()`` /
    ``hvd.guard_check()`` raise ``HorovodInternalError`` so the
    elastic restore contract fires. The no-skip path pays no host
    sync — the callback lives inside the skip branch only. The guard
    conds the whole inner update, so it requires a dtype-preserving
    inner transform (every elementwise optax chain is).

    ``local_sgd_steps=K`` (``None`` defers to
    ``HOROVOD_LOCAL_SGD_STEPS``; the mode engages at K > 1) switches
    the optimizer into local-SGD mode (horovod_tpu/local_sgd.py,
    ROADMAP item 3): every ``update`` exchanges gradients over the
    INTRA-slice replica groups only — fused, bucketed and monolithic
    paths alike, so the compiled step program carries zero
    inter-slice replica groups and every gradient byte stays on ICI —
    and the returned transformation gains a ``sync`` callable (see
    :class:`LocalSGDGradientTransformation`) that reconciles the
    parameter DELTAS since the last round across the inter (DCN) axis
    with hierarchical Adasum on the ``local_sgd_inter_wire``
    (default ``int8`` — EF residuals carried across rounds in the
    state's ``local_residual`` leaf). Params must ride the training
    loop RANK-MAJOR (``in_specs=P(hvd.WORLD_AXIS)``): slices diverge
    during the local phase, so a replicated ``P()`` spec would be a
    lie. K = 1 IS the existing path (bit-identical by construction).
    Sum/Average only; process sets don't compose. ``local_sgd_intra``
    injects an explicit chips-per-slice for the split (tests/bench on
    single-slice hosts; normal jobs let the topology resolve it).
    """
    op = resolve_op(op, average)
    from . import local_sgd as _local_sgd

    local_k = int(
        local_sgd_steps
        if local_sgd_steps is not None
        else _local_sgd.default_steps()
    )
    local_on = local_k > 1
    if local_on:
        if local_sgd_steps is None:
            # engaged via env: the caller may be an existing loop that
            # never drives the sync round — warn loudly once
            _local_sgd.warn_env_engaged(local_k)
        if op not in (Sum, Average):
            raise ValueError(
                "local_sgd_steps > 1 requires op=Sum/Average for the "
                "local phase (Adasum is the ROUND combiner, not the "
                "per-step gradient op)"
            )
        if process_set is not None and process_set.process_set_id != 0:
            raise NotImplementedError(
                "local_sgd_steps does not compose with process sets"
            )
        if local_sgd_inter_wire not in _local_sgd.INTER_WIRES:
            raise ValueError(
                f"unknown local_sgd_inter_wire {local_sgd_inter_wire!r}"
            )
    if gradient_predivide_factor != 1.0 and op != Average:
        raise ValueError(
            "gradient_predivide_factor requires op=Average (ref parity)"
        )
    if error_feedback and not getattr(compression, "quantized_wire", False):
        raise ValueError(
            "error_feedback=True requires a quantized-wire compression "
            "(Compression.int8)"
        )
    explicit_overlap = overlap_buckets is not None
    if overlap_buckets is None:
        overlap_buckets = overlap.default_buckets()
    overlap_buckets = int(overlap_buckets)
    if overlap_min_bytes is None:
        overlap_min_bytes = overlap.default_min_bytes()
    if overlap_buckets and op not in (Sum, Average):
        if explicit_overlap:
            raise ValueError(
                "overlap_buckets requires op=Sum/Average (Adasum/min/"
                "max/product do not commute with bucket concatenation)"
            )
        # HOROVOD_OVERLAP is a fleet-wide default: a job running an op
        # the bucketed layer can't carry keeps its monolithic path
        # instead of breaking
        overlap_buckets = 0
    k = int(backward_passes_per_step)
    if k < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    guard_on = (
        bool(grad_guard)
        if grad_guard is not None
        else _guard.default_enabled()
    )
    max_skips = int(
        guard_max_skips
        if guard_max_skips is not None
        else _guard.default_max_skips()
    )
    guard_src = _guard.new_source() if guard_on else 0

    def reduce_op_factors(n: int):
        if gradient_predivide_factor != 1.0 and op == Average:
            f = gradient_predivide_factor
            return ReduceOp.SUM, 1.0 / (n * f), f
        pre = prescale_factor if prescale_factor is not None else 1.0
        post = postscale_factor if postscale_factor is not None else 1.0
        return op, pre, post

    def _local_stages():
        """The two-level split for the traced axis (local mode only;
        raises when no split resolves — a one-slice local phase is
        the caller asking for a mode that cannot exist)."""
        return _local_sgd.resolve_stages(
            int(jax.lax.axis_size(axis_name)), intra=local_sgd_intra
        )

    def communicate(grads, seed, residuals=None):
        """Exchange + optional guard flag. Returns a uniform
        ``(reduced, new_residuals_or_None, finite_or_None)`` triple so
        the update paths never re-derive the unpacking rules."""
        groups = _local_stages()[0] if local_on else None
        n = (
            process_set.size
            if process_set is not None and process_set.process_set_id != 0
            else (
                len(groups[0]) if groups is not None
                else jax.lax.axis_size(axis_name)
            )
        )
        eff_op, pre, post = reduce_op_factors(n)
        if overlap_buckets:
            out = overlap.bucketed_allreduce(
                grads, op=eff_op, n_buckets=overlap_buckets,
                compression=compression, prescale_factor=pre,
                postscale_factor=post, process_set=process_set,
                axis_name=axis_name, seed=seed, residuals=residuals,
                min_bucket_bytes=overlap_min_bytes,
                return_finite=guard_on,
                groups=groups,
            )
            if guard_on:
                if residuals is not None:
                    return out
                reduced, finite = out
                return reduced, None, finite
            if residuals is not None:
                reduced, new_r = out
                return reduced, new_r, None
            return out, None, None
        out = _allreduce_grads(
            grads, eff_op, compression, pre, post, process_set, axis_name,
            seed=seed, residuals=residuals, groups=groups,
        )
        if residuals is not None:
            reduced, new_r = out
        else:
            reduced, new_r = out, None
        finite = traced.tree_finite(reduced) if guard_on else None
        return reduced, new_r, finite

    def guarded_apply(reduced, new_residual, finite, state, params):
        """The skip-step cond (common/guard.py): apply the inner
        update only when the reduced gradients are finite; otherwise
        zero updates, untouched inner state, the LAST APPLIED step's
        EF carry, and a host callback (skip branch only — the healthy
        path never reaches the host). Returns
        ``(updates, inner, residual, skips, streak)``."""
        streak_next = state.guard_streak + 1

        def apply(_):
            updates, inner = optimizer.update(reduced, state.inner, params)
            return (
                updates, inner, new_residual, state.guard_skips,
                jnp.zeros((), jnp.int32),
            )

        def skip(_):
            jax.debug.callback(
                functools.partial(
                    _guard.record_skip, max_skips=max_skips,
                    source=guard_src,
                ),
                streak_next, state.step,
            )
            zeros = jax.tree_util.tree_map(jnp.zeros_like, reduced)
            return (
                zeros, state.inner, state.residual,
                state.guard_skips + 1, streak_next,
            )

        return jax.lax.cond(finite, apply, skip, operand=None)

    def init_fn(params):
        inner = optimizer.init(params)
        zero = jnp.zeros((), jnp.int32)
        residual = (
            jax.tree_util.tree_map(jnp.zeros_like, params)
            if error_feedback
            else None
        )
        # guard counters ride the state pytree only when the guard is
        # on — None leaves are empty subtrees, so unguarded jobs keep
        # the exact state structure (and checkpoints) they had
        gskips = zero if guard_on else None
        gstreak = zero if guard_on else None
        # local-SGD round state: the anchor starts AT the initial
        # params (round 0's delta measures from here); the EF carry of
        # the int8 inter wire starts empty
        anchor = (
            jax.tree_util.tree_map(jnp.asarray, params)
            if local_on
            else None
        )
        local_res = (
            jax.tree_util.tree_map(jnp.zeros_like, params)
            if local_on and local_sgd_inter_wire == "int8"
            else None
        )
        if k == 1:
            return _AccumulationState(
                inner=inner, accum=None, counter=zero, step=zero,
                residual=residual, guard_skips=gskips,
                guard_streak=gstreak, local_anchor=anchor,
                local_residual=local_res,
            )
        accum = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _AccumulationState(
            inner=inner, accum=accum, counter=zero, step=zero,
            residual=residual, guard_skips=gskips, guard_streak=gstreak,
            local_anchor=anchor, local_residual=local_res,
        )

    def update_fn(grads, state: _AccumulationState, params=None):
        # Flight-recorder auto-threading (common/telemetry.py): one
        # step-boundary tick per compiled update, riding the SAME
        # internal step counter that seeds stochastic rounding — this
        # is how fully-jitted loops (where no host code runs per step)
        # still produce StepStats records. Gated at TRACE time: when
        # telemetry is off the compiled program carries nothing, and
        # enabling telemetry after compile needs a retrace (documented
        # in docs/observability.md).
        if _telemetry.auto_enabled():
            jax.debug.callback(_telemetry.device_step_tick, state.step)
        if k == 1:
            reduced, residual, finite = communicate(
                grads, state.step,
                residuals=state.residual if error_feedback else None,
            )
            if guard_on:
                updates, inner, residual, skips, streak = guarded_apply(
                    reduced, residual, finite, state, params
                )
                return updates, _AccumulationState(
                    inner=inner, accum=None, counter=state.counter,
                    step=state.step + 1, residual=residual,
                    guard_skips=skips, guard_streak=streak,
                    local_anchor=state.local_anchor,
                    local_residual=state.local_residual,
                )
            updates, inner = optimizer.update(reduced, state.inner, params)
            return updates, _AccumulationState(
                inner=inner, accum=None, counter=state.counter,
                step=state.step + 1, residual=residual,
                local_anchor=state.local_anchor,
                local_residual=state.local_residual,
            )

        # Local aggregation (`backward_passes_per_step` [V]): accumulate k
        # micro-grads, communicate once, step once; off-boundary
        # micro-steps emit zero updates. Like the reference, the SUM of the
        # k micro-grads is applied unless average_aggregated_gradients=True
        # (ref: gradient_aggregation defaults,
        # horovod/tensorflow/gradient_aggregation*.py [V]).
        accum = jax.tree_util.tree_map(
            lambda a, g: a + g, state.accum, grads
        )
        counter = state.counter + 1
        boundary = counter >= k

        def do_step(_):
            agg = (
                jax.tree_util.tree_map(lambda a: a / k, accum)
                if average_aggregated_gradients
                else accum
            )
            reduced, residual, finite = communicate(
                agg, state.step,
                residuals=state.residual if error_feedback else None,
            )
            zeroed = jax.tree_util.tree_map(jnp.zeros_like, accum)
            if guard_on:
                # a skipped boundary still clears the accumulator: the
                # poisoned micro-batch window is discarded, not replayed
                updates, inner, residual, skips, streak = guarded_apply(
                    reduced, residual, finite, state, params
                )
                return (
                    updates, inner, zeroed, jnp.zeros((), jnp.int32),
                    residual, skips, streak,
                )
            updates, inner = optimizer.update(reduced, state.inner, params)
            return (
                updates, inner, zeroed, jnp.zeros((), jnp.int32),
                residual, state.guard_skips, state.guard_streak,
            )

        def skip_step(_):
            zeros = jax.tree_util.tree_map(jnp.zeros_like, accum)
            return (
                zeros, state.inner, accum, counter, state.residual,
                state.guard_skips, state.guard_streak,
            )

        (
            updates, inner, accum_out, counter_out, residual_out,
            skips_out, streak_out,
        ) = jax.lax.cond(boundary, do_step, skip_step, operand=None)
        return updates, _AccumulationState(
            inner=inner, accum=accum_out, counter=counter_out,
            step=state.step + 1, residual=residual_out,
            guard_skips=skips_out, guard_streak=streak_out,
            local_anchor=state.local_anchor,
            local_residual=state.local_residual,
        )

    if not local_on:
        return optax.GradientTransformation(init_fn, update_fn)

    def sync_fn(params, state: _AccumulationState):
        """The K-step reconciliation round (compile as its OWN program
        — see LocalSGDGradientTransformation): parameter deltas since
        the last anchor merge across slices by hierarchical Adasum on
        the inter wire; params and anchor land on the consensus, the
        EF carry rolls to the next round."""
        stages = _local_stages()
        new_params, new_res = _local_sgd.sync_tree(
            params, state.local_anchor,
            residual=state.local_residual,
            stages=stages, axis_name=axis_name,
            inter_wire=local_sgd_inter_wire, seed=state.step,
            return_residual=local_sgd_inter_wire == "int8",
        )
        return new_params, state._replace(
            local_anchor=new_params, local_residual=new_res
        )

    return LocalSGDGradientTransformation(
        init_fn, update_fn, sync_fn, local_k
    )


# ---------------------------------------------------------------- tape API


def value_and_grad(
    fun: Callable,
    argnums=0,
    has_aux: bool = False,
    op: Optional[ReduceOp] = None,
    average: Optional[bool] = None,
    compression: Compressor = Compression.none,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
    overlap_buckets: Optional[int] = None,
    overlap_min_bytes: Optional[int] = None,
    **grad_kwargs,
):
    """jax.value_and_grad + gradient allreduce: the DistributedGradientTape
    equivalent (ref: horovod/tensorflow/__init__.py
    DistributedGradientTape._allreduce_grads [V], SURVEY.md §3.5).

    ``overlap_buckets=N`` is the in-backprop path: the differentiated
    argument passes through :func:`hvd.overlap_boundary` before use, so
    its cotangents leave through N independent per-bucket collectives
    DURING backprop — the returned gradients are already reduced, and
    the compiled step's collectives sit at their buckets' dataflow
    frontiers where XLA overlaps them with the remaining backward
    compute (the reference's autograd-hook latency hiding,
    arXiv 1802.05799 §3, as static dataflow). ``None`` defers to
    ``HOROVOD_OVERLAP``/``HOROVOD_OVERLAP_BUCKETS``; requires a single
    int ``argnums`` and op=Sum/Average.

    With ``compression=Compression.int8``, pass your step counter to the
    wrapped function as ``hvd_step=`` (a traced scalar is fine): it seeds
    the stochastic rounding so quantization noise varies across steps and
    stays unbiased over time. ``DistributedOptimizer`` threads its own
    step automatically; the tape API has no state, so when the caller
    does not provide one an INTERNAL per-wrapper call counter is
    threaded instead — correct in eager use, but constant-folded if the
    caller jits the wrapped function, so a warning (once) nudges jit
    users to thread a real step. Passing the SAME concrete seed twice
    also warns once: a repeated seed re-applies the identical stochastic
    rounding pattern every step, turning the unbiased quantizer into a
    biased one. Other compressors ignore it."""
    op = resolve_op(op, average)
    explicit_overlap = overlap_buckets is not None
    if overlap_buckets is None:
        overlap_buckets = overlap.default_buckets()
    overlap_buckets = int(overlap_buckets)
    if overlap_min_bytes is None:
        overlap_min_bytes = overlap.default_min_bytes()
    if overlap_buckets and (
        op not in (Sum, Average) or not isinstance(argnums, int)
    ):
        if explicit_overlap:
            if not isinstance(argnums, int):
                raise ValueError(
                    "overlap_buckets requires a single int argnums "
                    "(the boundary wraps one argument's pytree)"
                )
            raise ValueError(
                "overlap_buckets requires op=Sum/Average (Adasum/min/"
                "max/product do not commute with bucket concatenation)"
            )
        # env-default overlap: unsupported shapes keep the monolithic
        # path instead of breaking (same rationale as the optimizer)
        overlap_buckets = 0
    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux, **grad_kwargs)
    auto_step = itertools.count()
    seen = {"last": None, "warned": False}
    quantized = getattr(compression, "quantized_wire", False)

    def _resolve_seed(args, kwargs, hvd_step):
        if not quantized:
            return 0 if hvd_step is None else hvd_step
        if hvd_step is None:
            step = next(auto_step)
            # Tracer detection: a cheap shallow scan on EVERY call —
            # top-level args plus one level into dict/list/tuple args,
            # which covers the params-pytree idiom — catches
            # eager-calls-then-jit (trace at step > 0); a full pytree
            # flatten runs on the FIRST call only, so deeply nested
            # leaves are caught at jit-from-the-start without paying
            # O(n_leaves) per eager step forever.
            def _shallow(objs):
                for a in objs:
                    if isinstance(a, dict):
                        yield from a.values()
                    elif isinstance(a, (list, tuple)):
                        yield from a
                    else:
                        yield a

            traced_call = any(
                isinstance(a, jax.core.Tracer)
                for a in _shallow(list(args) + list(kwargs.values()))
            )
            if not traced_call and step == 0:
                traced_call = any(
                    isinstance(leaf, jax.core.Tracer)
                    for leaf in jax.tree_util.tree_leaves((args, kwargs))
                )
            if not seen["warned"] and traced_call:
                seen["warned"] = True
                warnings.warn(
                    "hvd.value_and_grad(compression=int8) is being traced "
                    "(jit) without hvd_step=; the auto-threaded step "
                    "counter constant-folds into the compiled program, so "
                    "every step reuses one stochastic-rounding pattern. "
                    "Pass your step counter as hvd_step= (a traced scalar "
                    "is fine).",
                    stacklevel=3,
                )
            return step
        if isinstance(hvd_step, int):
            if not seen["warned"] and seen["last"] == hvd_step:
                seen["warned"] = True
                warnings.warn(
                    f"hvd.value_and_grad(compression=int8) received the "
                    f"same hvd_step={hvd_step} twice: a constant seed "
                    f"repeats the stochastic-rounding pattern every step "
                    f"(biased over time). Thread an incrementing step "
                    f"counter.",
                    stacklevel=3,
                )
            seen["last"] = hvd_step
        return hvd_step

    def _auto_telemetry_begin(hvd_step) -> bool:
        """Open a flight-recorder step around this (host-side) call —
        the tape-API half of telemetry auto-threading. Skipped under
        tracing (a jitted wrapper runs this body once, at trace time —
        the optimizer's debug-callback tick owns that case) and when a
        step is already open (explicit hvd.step_begin wins)."""
        if not _telemetry.auto_enabled():
            return False
        try:
            if not jax.core.trace_state_clean():
                return False
        except Exception:
            pass
        step = hvd_step if isinstance(hvd_step, int) else None
        return _telemetry.hub().auto_step_begin(step)

    def wrapped(*args, hvd_step=None, **kwargs):
        seed = _resolve_seed(args, kwargs, hvd_step)
        opened = _auto_telemetry_begin(hvd_step)
        if (
            not opened
            and hvd_step is not None
            and _telemetry.auto_enabled()
        ):
            # Traced call (the usual shape: vg inside jit/shard_map): a
            # host-side record is impossible — this body runs ONCE, at
            # trace time — but a THREADED step counter lets the
            # compiled program tick the flight recorder instead, same
            # mechanism as the optimizer's auto-threading. A concrete
            # constant hvd_step under jit collapses to one record (the
            # quantized-seed warning above covers that misuse).
            try:
                under_trace = not jax.core.trace_state_clean()
            except Exception:
                under_trace = False
            if under_trace:
                # source "tape": these ids are the CALLER's step
                # counter, so they outrank the optimizer's internal
                # ticks — when both fire in one program only one
                # source drives the recorder (hub.tick dedup)
                jax.debug.callback(
                    functools.partial(
                        _telemetry.device_step_tick, source="tape"
                    ),
                    hvd_step,
                )
        try:
            return _wrapped_body(args, kwargs, seed)
        finally:
            if opened:
                _telemetry.hub().auto_step_end()

    def _wrapped_body(args, kwargs, seed):
        if overlap_buckets:
            # in-backprop exchange: grads come back ALREADY reduced —
            # the boundary's custom_vjp emitted the per-bucket
            # collectives inside the backward pass
            def fun2(*a, **k):
                a = list(a)
                a[argnums] = overlap.overlap_boundary(
                    a[argnums], op=op, n_buckets=overlap_buckets,
                    compression=compression, process_set=process_set,
                    axis_name=axis_name, seed=seed,
                    min_bucket_bytes=overlap_min_bytes,
                )
                return fun(*a, **k)

            vg2 = jax.value_and_grad(
                fun2, argnums=argnums, has_aux=has_aux, **grad_kwargs
            )
            return vg2(*args, **kwargs)
        val, grads = vg(*args, **kwargs)
        grads = _allreduce_grads(
            grads, op, compression, 1.0, 1.0, process_set, axis_name,
            seed=seed,
        )
        return val, grads

    return wrapped


def grad(fun: Callable, **kwargs):
    vg = value_and_grad(fun, **kwargs)

    def wrapped(*args, **kw):
        _, g = vg(*args, **kw)
        return g

    return wrapped


# ------------------------------------------------- parameter broadcast API


def broadcast_parameters(params, root_rank: int = 0):
    """Make every rank hold root_rank's parameters
    (ref: horovod/torch/functions.py broadcast_parameters /
    tensorflow broadcast_variables [V], SURVEY.md §5.4).

    TPU-native semantics, two cases per leaf:

    * **host / replicated leaf** — placing it with a replicated sharding
      sourced from the controller's copy IS the broadcast; XLA moves the
      bytes over ICI (under a single controller there is exactly one
      source copy, so root_rank is moot).
    * **rank-major leaf** (leading dim = world, sharded over the world
      axis — the eager convention for per-rank-divergent state): every
      rank's row is overwritten with ``root_rank``'s, which is the
      reference's actual semantics (rank 0 may have restored a
      checkpoint the others don't have)."""
    from .common import basics
    from .common.topology import WORLD_AXIS, replicated_sharding

    mesh = basics.mesh()
    world = int(mesh.devices.size)
    sharding = replicated_sharding(mesh)

    def _rank_major(x) -> bool:
        if not isinstance(x, jax.Array) or x.ndim == 0:
            return False
        if x.shape[0] != world:
            return False
        spec = getattr(x.sharding, "spec", None)
        return bool(spec) and spec[0] == WORLD_AXIS

    def one(x):
        if _rank_major(x):
            root = jax.device_put(x[root_rank], sharding)
            # All rows = root's; re-place with the ORIGINAL rank-major
            # sharding so per-device memory stays 1/world of the buffer
            # and a second broadcast still recognizes the leaf.
            return jax.device_put(
                jnp.broadcast_to(root[None], x.shape), x.sharding
            )
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(one, params)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Replicate optimizer state (ref: broadcast_optimizer_state [V]).
    Same mechanism as broadcast_parameters — optax states are pytrees."""
    return broadcast_parameters(opt_state, root_rank)


def broadcast_object(obj, root_rank: int = 0, name: Optional[str] = None):
    """Arbitrary-object broadcast (ref: horovod/torch/functions.py
    broadcast_object, pickle-over-collective [V]). Under a single
    controller every rank already shares the controller's Python objects;
    in multi-controller jobs the runner's rendezvous KV store carries the
    pickled payload (runner/rendezvous.py)."""
    import jax as _jax

    if _jax.process_count() == 1:
        return obj
    from .runner.rendezvous import broadcast_via_kv  # pragma: no cover

    return broadcast_via_kv(obj, root_rank, name)  # pragma: no cover


def allgather_object(obj, name: Optional[str] = None):
    """Gather one arbitrary object per rank into a list ordered by rank
    (ref: horovod/torch/functions.py allgather_object,
    pickle-over-allgather [V]). Under the single controller this process
    speaks for every rank, so the list is [obj] * size; multi-controller
    jobs gather pickles through the rendezvous KV like broadcast_object.
    """
    import jax as _jax

    from .common import basics

    if _jax.process_count() == 1:
        world = basics.size() if basics.is_initialized() else 1
        return [obj] * world
    from .runner.rendezvous import allgather_via_kv  # pragma: no cover

    return allgather_via_kv(obj, name)  # pragma: no cover
