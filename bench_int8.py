"""int8 quantized-allreduce compute-tax microbenchmark (VERDICT r4
item 5 / Weak #4).

`traced.quantized_allreduce`'s wire claim ("true ~4x fewer bytes on
ICI") is a byte model; single-chip hardware can't prove busbw, but the
KERNEL-SIDE cost — two stochastic-rounding quantize stages (Pallas
`int8_quantize`), dequant-sum, and the optional error-feedback residual
— is measurable today and decides whether the wire win survives at
real link speeds. This harness times, per payload size:

  * plain  — `traced.allreduce` (psum; folds to a copy at world=1)
  * quant  — `traced.quantized_allreduce`
  * quant_ef — the same with `return_residual=True` (EF carry)

and prints per size one JSON line:
  {"metric": "int8_compute_tax", "bytes": N, "value": quant_ms/plain_ms,
   "plain_ms": ..., "quant_ms": ..., "quant_ef_ms": ..., "ef_over_quant": ...}

Abort criterion for the docs (docs/perf.md): at a v5e-class ICI rate,
int8 wins only if (quant_ms − plain_ms) < 0.75 · wire_time_fp32(bytes)
· ring_factor — the tax must undercut the bytes it saves.

Env: BENCH_SIZES (bytes, comma-sep; default 1,4,16,64,256 MiB),
BENCH_ITERS (default 20), BENCH_PLATFORM=cpu for the simulated mesh
(sim lines carry the quarantine note).
"""

import json
import os
import time
from functools import partial

_SIM_NOTE = (
    "logic-validation only (CPU simulation); NOT a TPU kernel-cost "
    "number"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from _benchlib import sync as _sync
    from horovod_tpu.common.topology import WORLD_AXIS
    from horovod_tpu.ops import traced
    from horovod_tpu.ops.reduction_ops import Average

    devices = jax.devices()
    world = len(devices) if devices[0].platform != "tpu" else 1
    mesh = Mesh(np.array(devices[:world]), (WORLD_AXIS,))
    platform = devices[0].platform
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    sizes_env = os.environ.get("BENCH_SIZES")
    if sizes_env:
        sizes = [int(s) for s in sizes_env.split(",")]
    else:
        sizes = [1 << 20, 4 << 20, 16 << 20, 64 << 20, 256 << 20]

    def timed(step, x):
        x = step(step(x))  # compile fresh + committed-input variants
        _sync(x)
        t0 = time.perf_counter()
        for _ in range(iters):
            x = step(x)
        _sync(x)
        return (time.perf_counter() - t0) / iters * 1e3

    for nbytes in sizes:
        n = max(nbytes // 4, 1)

        def shmap(fn):
            return jax.jit(
                partial(
                    jax.shard_map,
                    mesh=mesh,
                    in_specs=P(WORLD_AXIS),
                    out_specs=P(WORLD_AXIS),
                    check_vma=False,
                )(fn)
            )

        plain = shmap(
            lambda x: traced.allreduce(x[0], op=Average)[None]
        )
        quant = shmap(
            lambda x: traced.quantized_allreduce(x[0], op=Average)[None]
        )

        def _ef(x):
            out, res = traced.quantized_allreduce(
                x[0], op=Average, return_residual=True
            )
            # fold the residual back in the way the EF optimizer does —
            # the carry must stay live, not be DCE'd
            return (out + 1e-6 * res)[None]

        quant_ef = shmap(_ef)

        x0 = jnp.asarray(
            np.random.default_rng(0)
            .normal(size=(world, n))
            .astype(np.float32)
        )
        ms_plain = timed(plain, x0)
        ms_quant = timed(quant, x0)
        ms_ef = timed(quant_ef, x0)
        line = {
            "metric": "int8_compute_tax",
            "bytes": nbytes,
            "world": world,
            "value": round(ms_quant / ms_plain, 3),
            "unit": "x",
            "plain_ms": round(ms_plain, 3),
            "quant_ms": round(ms_quant, 3),
            "quant_ef_ms": round(ms_ef, 3),
            "ef_over_quant": round(ms_ef / ms_quant, 3),
            "platform": platform,
        }
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
