"""ZeRO-1/2/3 A/B bench (sharded_optimizer.py, PR 9).

Measures what each sharding stage buys and costs on the SAME deep-MLP
training step, at world=4 (the acceptance geometry; falls back to the
full device count when fewer than 4 devices exist):

* ``ab_zero1`` — the baseline: full params, classic
  ``jax.value_and_grad`` (full gradient tree at the exchange barrier),
  ZeRO-1 shard update.
* ``ab_zero2`` — gradient sharding: ``opt.value_and_grad``'s
  in-backprop bucketed reduce-scatter lands grads directly in shard
  storage; params stay replicated.
* ``ab_zero3`` — parameter sharding: params live as shard rows,
  forward-interleaved per-bucket all-gathers, local shard apply.

Each leg appends one JSON artifact under BENCH_ARTIFACT_DIR (default
bench_results/zero/) carrying:

* ``value`` — ms/step (honest value-dependency sync, _benchlib.sync);
* ``collectives`` — lowered-module counts (all_reduce /
  reduce_scatter / all_gather): the compiled-program evidence;
* live-buffer accounting for params+grads, per rank:
  - ``resident_params_bytes`` — what must sit in HBM across steps,
  - ``grad_storage_bytes`` — reduced-gradient residency,
  - ``transient_exchange_bytes`` — peak in-step transient under the
    bucket schedule (full grad tree for the monolithic zero1 barrier;
    one bucket pane for the in-backprop legs),
  - ``live_params_grads_bytes`` — their sum: the A/B number. The
    acceptance gate (ZeRO-3 ≥ 1.8× below ZeRO-1 at world=4) is
    asserted in BENCH_DRYRUN so ``./ci.sh bench-smoke`` trips on a
    layout regression;
* ``memory_analysis`` — XLA's compiled-module view (argument / output
  / temp bytes) when the backend exposes it — the whole-step measured
  counterpart (includes activations, so it is reported, not gated).

CPU lines carry the quarantine note — wall-clock claims need the
on-chip capture; the dryrun validates harness + HLO shape + byte
accounting. Env: BENCH_LAYERS / BENCH_WIDTH / BENCH_BUCKETS /
BENCH_ITERS / BENCH_DRYRUN / BENCH_ARTIFACT_DIR.
"""

import json
import os
import time

from _benchlib import stamp as _stamp
from functools import partial

_SIM_NOTE = (
    "logic-validation only (CPU simulation); step-time is NOT a TPU "
    "wall-clock number — byte accounting and HLO shape are exact"
)


def _collective_counts(lowered) -> dict:
    """Lowered-module collective counts via the shared
    horovod_tpu.analysis parser (same gate as tests/test_zero)."""
    from horovod_tpu import analysis

    return analysis.parse_module(lowered).counts()


def _memory_analysis(compiled):
    try:
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
    except Exception:
        return None


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.ops import overlap

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    iters = int(os.environ.get("BENCH_ITERS", "2" if dryrun else "30"))
    layers = int(os.environ.get("BENCH_LAYERS", "4" if dryrun else "16"))
    width = int(os.environ.get("BENCH_WIDTH", "64" if dryrun else "1024"))
    n_buckets = int(os.environ.get("BENCH_BUCKETS", "4"))
    batch = 8 if dryrun else 64

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "zero")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    hvd.init()
    # the acceptance geometry is world=4: carve a 4-chip submesh when
    # the slice is bigger (the optimizer takes world= explicitly)
    world = min(4, len(jax.devices()))
    mesh = Mesh(np.asarray(jax.devices()[:world]), (hvd.WORLD_AXIS,))
    ax = hvd.WORLD_AXIS
    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    params_host = {
        f"w{i:02d}": (
            rng.normal(size=(width, width)) / np.sqrt(width)
        ).astype(np.float32)
        for i in range(layers)
    }
    x = jnp.asarray(
        rng.normal(size=(world, batch, width)), jnp.float32
    )
    y = jnp.asarray(rng.normal(size=(world, batch, width)), jnp.float32)
    param_bytes = sum(
        int(np.prod(p.shape)) * 4 for p in params_host.values()
    )
    leaves = list(params_host.values())
    sched = overlap.build_bucket_schedule(leaves, n_buckets, 0)
    max_bucket = max(sched.bucket_bytes) if sched.bucket_bytes else 0
    shard_bytes = sum(
        -(-int(np.prod(p.shape)) // world) * 4
        for p in params_host.values()
    )

    def fresh_params():
        return {k: jnp.asarray(v) for k, v in params_host.items()}

    def loss_fn(p, xb, yb):
        h = xb
        for k in sorted(p):
            h = jnp.tanh(h @ p[k])
        return jnp.mean((h - yb) ** 2)

    def emit(leg, ms, counts, accounting, mem):
        line = {
            "metric": "zero_ab",
            "leg": leg,
            "world": world,
            "layers": layers,
            "width": width,
            "n_buckets": n_buckets,
            "param_bytes": param_bytes,
            "value": round(ms, 3),
            "unit": "ms/step",
            "platform": platform,
            "collectives": counts,
            **accounting,
        }
        if mem:
            line["memory_analysis"] = mem
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)
        with open(
            os.path.join(artifact_dir, f"zero_{leg}.json"), "a"
        ) as f:
            f.write(json.dumps(_stamp(line)) + "\n")
        return line

    def timed(step, carry):
        carry = step(carry)  # compile + warm
        _sync(carry)
        t0 = time.perf_counter()
        for _ in range(iters):
            carry = step(carry)
        _sync(carry)
        return (time.perf_counter() - t0) / iters * 1e3

    def accounting(stage, param_store):
        """Params residency MEASURED from the actual arrays the step
        consumes (a stage-3 layout regression back to replicated
        params shows up here as real bytes, not as stage arithmetic);
        the in-step transients are modeled from the bucket schedule
        (full grad tree at zero1's monolithic vg barrier; one bucket
        pane per in-backprop leg; gather+cotangent panes for zero3)."""
        leaves = jax.tree_util.tree_leaves(param_store)
        if stage <= 2:
            resident = sum(l.nbytes for l in leaves)  # replicated
        else:
            # [world, cols] rows: per-rank residency is one row
            resident = sum(l.nbytes // l.shape[0] for l in leaves)
        grads = 0 if stage == 1 else shard_bytes
        transient = (
            param_bytes if stage == 1
            else max_bucket if stage == 2
            else 2 * max_bucket
        )
        return {
            "resident_params_bytes": resident,
            "grad_storage_bytes": grads,
            "transient_exchange_bytes": transient,
            "live_params_grads_bytes": resident + grads + transient,
        }

    lines = {}

    # ---- leg 1: ZeRO-1, monolithic full-grad barrier
    o1 = hvd.ShardedDistributedOptimizer(
        optax.adam(1e-3), world=world,
        overlap_buckets=n_buckets, overlap_min_bytes=0,
    )
    p0 = fresh_params()
    s0 = o1.init(p0)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(), o1.state_spec()), P(ax), P(ax)),
        out_specs=(P(), o1.state_spec()),
        check_vma=False,
    )
    def z1step(carry, xb, yb):
        p, st = carry
        _, g = jax.value_and_grad(loss_fn)(p, xb[0], yb[0])
        u, st = o1.update(g, st, p)
        return optax.apply_updates(p, u), st

    z1 = jax.jit(z1step, donate_argnums=0)
    carry = (p0, s0)
    acct = accounting(1, p0)  # before donation invalidates p0
    low = z1.lower(carry, x, y)
    mem = _memory_analysis(low.compile())
    ms = timed(lambda c: z1(c, x, y), carry)
    lines["ab_zero1"] = emit(
        "ab_zero1", ms, _collective_counts(low), acct, mem,
    )

    # ---- leg 2: ZeRO-2, in-backprop scatter into shard storage
    o2 = hvd.ShardedDistributedOptimizer(
        optax.adam(1e-3), world=world, zero_stage=2,
        overlap_buckets=n_buckets, overlap_min_bytes=0,
    )
    p0 = fresh_params()
    s0 = o2.init(p0)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=((P(), o2.state_spec()), P(ax), P(ax)),
        out_specs=(P(), o2.state_spec()),
        check_vma=False,
    )
    def z2step(carry, xb, yb):
        p, st = carry
        _, g_sh = o2.value_and_grad(loss_fn)(p, xb[0], yb[0])
        u, st = o2.update(g_sh, st, p)
        return optax.apply_updates(p, u), st

    z2 = jax.jit(z2step, donate_argnums=0)
    carry = (p0, s0)
    acct = accounting(2, p0)
    low = z2.lower(carry, x, y)
    mem = _memory_analysis(low.compile())
    ms = timed(lambda c: z2(c, x, y), carry)
    lines["ab_zero2"] = emit(
        "ab_zero2", ms, _collective_counts(low), acct, mem,
    )

    # ---- leg 3: ZeRO-3, sharded params + forward-interleaved gathers
    o3 = hvd.ShardedDistributedOptimizer(
        optax.adam(1e-3), world=world, zero_stage=3,
        overlap_buckets=n_buckets, overlap_min_bytes=0,
    )
    p0 = fresh_params()
    ps0 = o3.init_params(p0)
    s0 = o3.init(p0)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=((o3.state_spec(), o3.state_spec()), P(ax), P(ax)),
        out_specs=(o3.state_spec(), o3.state_spec()),
        check_vma=False,
    )
    def z3step(carry, xb, yb):
        psh, st = carry
        local = o3.local_shards(psh)
        _, g_sh = o3.value_and_grad(loss_fn)(local, xb[0], yb[0])
        u, st = o3.update(g_sh, st, local)
        return o3.as_rows(optax.apply_updates(local, u)), st

    z3 = jax.jit(z3step, donate_argnums=0)
    carry = (ps0, s0)
    acct = accounting(3, ps0)
    low = z3.lower(carry, x, y)
    mem = _memory_analysis(low.compile())
    ms = timed(lambda c: z3(c, x, y), carry)
    lines["ab_zero3"] = emit(
        "ab_zero3", ms, _collective_counts(low), acct, mem,
    )

    ratio = (
        lines["ab_zero1"]["live_params_grads_bytes"]
        / lines["ab_zero3"]["live_params_grads_bytes"]
    )
    print(
        json.dumps(
            {
                "metric": "zero_live_buffer_ratio",
                "zero1_over_zero3": round(ratio, 3),
                "gate": 1.8,
                "world": world,
            }
        ),
        flush=True,
    )
    if dryrun and world >= 4:
        # the acceptance gate rides the CI smoke: a layout regression
        # (params replicating again, schedule collapsing to one
        # bucket) trips here
        assert ratio >= 1.8, (
            f"ZeRO-3 live params+grads only {ratio:.2f}x below ZeRO-1 "
            "(acceptance gate: 1.8x at world=4)"
        )
        c3 = lines["ab_zero3"]["collectives"]
        assert c3["all_gather"] == n_buckets, c3
        assert c3["reduce_scatter"] == n_buckets, c3
        # the measured counterpart: XLA's own view of the step's
        # argument bytes must shrink when params stop replicating
        m1 = lines["ab_zero1"].get("memory_analysis")
        m3 = lines["ab_zero3"].get("memory_analysis")
        if m1 and m3:
            assert m3["argument_bytes"] < m1["argument_bytes"], (m1, m3)


if __name__ == "__main__":
    main()
